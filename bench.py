"""Benchmark harness: decode throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Two modes:

- **Suite mode** (bare ``python bench.py``, what the driver runs): an
  orchestrator that runs each measurement in a FRESH subprocess with
  backend-bring-up retries and a fallback config ladder, then emits one
  JSON line whose ``detail.rows`` carries every row — the flagship
  TinyLlama decode rate plus the BASELINE.md north-star rows
  (Llama-3-8B-Instruct int8/int4 single-chip decode, a 1-stage recurrent
  ring row).  Designed to be un-losable: a backend-init failure is retried
  after a sleep in a new interpreter; a config that fails walks down a
  batch/chunk ladder; a timeout (the known mid-compile wedge trigger on
  the remote-tunnel backend) stops further device work but still emits
  whatever was measured; if the TPU never comes up the flagship row runs
  on the CPU backend, clearly marked.  The process exits 0 with a JSON
  line on stdout in every one of those cases.

- **Direct mode** (``python bench.py --direct [flags]``): one in-process
  measurement, used by the suite's children and for manual sweeps.
  Flags: --model/--batch/--prompt-len/--new-tokens/--pipeline N/
  --quantize/--kv-dtype/--chunk/--mode prefill/--profile DIR.

Baselines (vs_baseline): TinyLlama-class rows compare against ~7 tokens/s
aggregate — the 3×Jetson-TX2 TinyLlama rate read off the reference's
published tokens-vs-time plot (assets/time_vs_tokens_TinyLlama.png; no
numeric tables exist — BASELINE.md).  Llama-3-8B rows compare against a
STATED Jetson-class stand-in of 40 tokens/s — the public Jetson AI Lab /
MLC figure for Llama-3-8B int4 on a Jetson AGX Orin — because the
reference never ran an 8B model (its TX2 testbed tops out at GPT-2 XL
1.56B); BASELINE.md's north star asks for >=1.5x a Jetson-Orin-class
baseline, i.e. >=60 tokens/s.
"""

import argparse
import contextlib
import json
import os
import subprocess
import sys
import time
from functools import partial

REFERENCE_TOKENS_PER_S = 7.0  # 3×Jetson TX2, TinyLlama, from the plot
JETSON_8B_TOKENS_PER_S = 40.0  # stated stand-in: AGX Orin Llama-3-8B int4
NORTH_STAR_MULTIPLE = 1.5  # BASELINE.md: >=1.5x the Jetson-class baseline

# The CompileGuard for the in-flight direct measurement (run_direct wraps
# every mode in one).  Modes call _mark_warm() at their warmup boundary;
# decode rows FAIL on any post-warmup recompile (the mdi-lint contract:
# the steady state must never build a new executable — docs/analysis.md),
# and every row records the counts in detail.compiles.
_GUARD = None


def _mark_warm():
    if _GUARD is not None:
        _GUARD.mark_warm()


def baseline_for(model: str) -> float:
    return JETSON_8B_TOKENS_PER_S if "8b" in model.lower() else REFERENCE_TOKENS_PER_S


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--direct", action="store_true",
                    help="run ONE measurement in-process (suite children use this)")
    ap.add_argument("--probe", action="store_true",
                    help="with --direct: only bring up the backend and run a tiny matmul")
    # The budget must finish WELL inside whatever timeout wraps the driver's
    # `python bench.py` call: the suite prints its single JSON line only at
    # the end, so an external kill loses every banked row.  Driver tolerance
    # beyond ~1 h is unproven; 3600 s of row starts (worst-case wall ~80 min
    # when the last row runs its full per-row timeout) keeps the flagship +
    # 8B north-star rows safe on a cold cache, and with a warm .jax_cache/
    # the whole 6-row suite fits in a few hundred seconds anyway.  Manual
    # sessions wanting every row cold can pass a bigger --suite-budget.
    ap.add_argument("--suite-budget", type=float, default=3600.0,
                    help="suite mode: stop launching new rows after this many seconds")
    ap.add_argument("--rows", default=None,
                    help="suite mode: comma-separated row names to run (default all)")
    # Probe budget: BENCH_r05 burned 900 s of a 1140 s suite on probe
    # timeouts before the CPU fallback — the budget is a HARD TOTAL cap
    # (180 s across every attempt AND retry sleep, not per attempt) and
    # env-overridable for sessions that KNOW the tunnel needs a long
    # bring-up (MDI_BENCH_PROBE_TIMEOUT / MDI_BENCH_PROBE_RETRIES mirror
    # the flags for driver-run suites).
    def _env_num(name, cast, fallback):
        # a malformed env value must degrade to the default, not kill every
        # bench invocation at parser construction
        try:
            return cast(os.environ.get(name, fallback))
        except (TypeError, ValueError):
            print(f"bench: ignoring malformed {name}={os.environ[name]!r}",
                  file=sys.stderr)
            return fallback

    ap.add_argument("--probe-timeout", type=float,
                    default=_env_num("MDI_BENCH_PROBE_TIMEOUT", float, 180.0),
                    help="suite mode: HARD TOTAL probe budget (s) across all "
                    "attempts and retry sleeps — the CPU fallback starts the "
                    "moment it expires; env MDI_BENCH_PROBE_TIMEOUT overrides "
                    "the default")
    ap.add_argument("--probe-retries", type=int,
                    default=_env_num("MDI_BENCH_PROBE_RETRIES", int, 1),
                    help="suite mode: probe attempts AFTER the first (each "
                    "separated by a 60 s sleep); env MDI_BENCH_PROBE_RETRIES "
                    "overrides the default")
    ap.add_argument("--doctor", action="store_true",
                    help="suite mode: run the staged `mdi-doctor --quick` "
                    "backend triage (each stage its own subprocess under a "
                    "hard timeout) before probing, and embed the health "
                    "snapshot as detail.doctor — diagnostic only, the "
                    "--probe result still decides the CPU fallback")
    ap.add_argument("--backend", choices=("auto", "cpu"), default="auto",
                    help="cpu: force the CPU backend via jax.config (the "
                    "JAX_PLATFORMS env var is pinned to the TPU plugin by "
                    "this image's sitecustomize, so only the config-update "
                    "route avoids touching a wedged tunnel backend)")
    ap.add_argument("--model", default="tiny-llama-1.1b")
    # decode is weight-bandwidth-bound so throughput grows with batch: v5e
    # r3 measured 880 (B=8) / 2283 (B=16) / 2727 (B=24) tok/s/chip.
    ap.add_argument("--batch", type=int, default=24)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--pipeline", type=int, default=0, help="run N-stage pipeline engine")
    ap.add_argument(
        "--samples-per-slot", type=int, default=1,
        help="pipeline mode: samples batched per ring slot (M)",
    )
    ap.add_argument("--dtype", choices=("bfloat16", "float16", "float32"), default="bfloat16")
    ap.add_argument("--quantize", choices=("none", "int8", "w8a8", "int4"), default="none")
    ap.add_argument("--kv-dtype",
                    choices=("auto", "bfloat16", "float16", "float32",
                             "float8", "int8"),
                    default="auto",
                    help="KV storage dtype; int8 (serve/kernel modes) "
                    "quantizes the paged pool — int8 blocks with per-block-"
                    "per-head scales dequantized inside the attention "
                    "kernels, ~2x pool blocks per HBM byte (docs/perf.md "
                    "'Quantized paged KV')")
    # decode default 256 measured 2283 tok/s/chip vs 2133 at 128 (v5e, r3):
    # longer scans amortize the host sync between dispatches.  Pipeline mode
    # defaults to 16: surplus ring rotations after a mid-chunk sample finish
    # are discarded, so long chunks deflate runs with early-stopping samples.
    ap.add_argument(
        "--chunk", type=int, default=None,
        help="decode steps per jit call (default 256; pipeline mode: "
        "steady-state ring rotations per jit call, default 16)",
    )
    ap.add_argument(
        "--mode",
        choices=("decode", "prefill", "train", "serve", "serve-open",
                 "kernel"),
        default="decode",
        help="prefill: compare flash-attention prefill latency vs the XLA "
        "path at --prompt-len and verify greedy-token agreement; "
        "train: time optimizer steps on synthetic data (tokens/s + MFU) — "
        "on TPU with --seq-len >= 2048 this exercises the Pallas flash "
        "custom_vjp forward+backward on hardware; "
        "serve: continuous-batching throughput over the paged KV pool on a "
        "mixed-length synthetic request trace (tokens/s + KV-block "
        "utilization; --batch = decode slots, --new-tokens = per-request "
        "output ceiling); "
        "kernel: paged-attention microbench — Pallas kernel vs gather "
        "fallback vs dense attention for decode/ragged-verify/ragged-"
        "prefill dispatch shapes at fp AND int8 (the in-kernel dequant "
        "cost measured, not asserted; kernel timings need a TPU backend); "
        "serve-open: OPEN-SYSTEM serving — Poisson arrivals through the "
        "async front-end (server/frontend.py) sweep offered load to find "
        "the max QPS whose p99 TTFT/TPOT meet the --slo-* ceilings "
        "(docs/serving.md 'Open-loop benchmarking')",
    )
    ap.add_argument("--serve-open-qps", default=None, metavar="Q1,Q2,...",
                    help="serve-open mode: comma-separated offered-load "
                    "grid (requests/s), swept ascending until the SLO is "
                    "missed.  Default: auto — a closed replay calibrates "
                    "the service capacity and the grid brackets it at "
                    "[0.25, 0.5, 0.75, 1.0, 1.25]x")
    ap.add_argument("--serve-open-requests", type=int, default=None,
                    help="serve-open mode: arrivals per sweep point "
                    "(default 3x --batch); each point offers this many "
                    "Poisson arrivals at its QPS and drains fully")
    ap.add_argument("--slo-ttft-ms", type=float, default=2000.0,
                    help="serve-open mode: p99 time-to-first-token "
                    "ceiling (ms) a sweep point must meet")
    ap.add_argument("--slo-tpot-ms", type=float, default=500.0,
                    help="serve-open mode: p99 time-per-output-token "
                    "ceiling (ms) a sweep point must meet")
    ap.add_argument("--serve-requests", type=int, default=None,
                    help="serve mode: queued requests (default 4x --batch)")
    ap.add_argument("--serve-block-size", type=int, default=16,
                    help="serve mode: KV pool block width (tokens)")
    ap.add_argument("--serve-chunk", type=int, default=8,
                    help="serve mode: device decode steps per host sync "
                    "(ServingConfig.decode_chunk; 1 = per-step engine)")
    ap.add_argument("--serve-token-budget", type=int, default=None,
                    help="serve mode: unified-step token budget "
                    "(ServingConfig.token_budget; decode lanes + prefill "
                    "chunk tokens per mixed dispatch; default "
                    "max_batch + prefill_chunk)")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="serve mode: speculative draft length (0 "
                    "disables).  Exact-match verify at temperature 0 "
                    "(token-identical to plain decode); rejection-sampled "
                    "verify at temperature>0 (distribution-preserving)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="serve mode: sampling temperature (0 = greedy; "
                    ">0 makes decode/verify draw from the filtered "
                    "distribution)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="serve mode: top-k sampling filter "
                    "(ServingConfig.top_k)")
    ap.add_argument("--top-p", type=float, default=None,
                    help="serve mode: nucleus sampling filter "
                    "(ServingConfig.top_p)")
    ap.add_argument("--draft-model", default=None, metavar="NAME",
                    help="serve mode: registry name of a small draft "
                    "model for speculative decode — drafts spec_k tokens "
                    "per slot in one jitted scan from a second paged "
                    "pool carved out of the block budget (random-init "
                    "params: fine for throughput rows, useless accept "
                    "rates on real text)")
    ap.add_argument("--serve-pool-mib", type=float, default=None,
                    help="serve mode: cap the KV pool at this many MiB — "
                    "max_blocks = budget // itemized bytes-per-block "
                    "(ServingConfig.block_bytes, scale arrays included), "
                    "so fp and int8 rows at the same budget compare "
                    "resident capacity at EQUAL pool bytes (default: "
                    "full coverage, no cap)")
    ap.add_argument("--serve-host-pool-mib", type=int, default=0,
                    help="serve mode: host-RAM KV block tier in MiB (0 = "
                    "off).  When set, the timed engine swaps preemption "
                    "victims' blocks to pinned host slabs and spills cold "
                    "prefix chains there — and the row ALSO runs "
                    "recompute-only and swap-only twins on the same trace "
                    "before the warm mark, recording the three-way "
                    "head-to-head in detail.tier")
    ap.add_argument("--host-link-gbps", type=float, default=None,
                    help="serve mode: host<->device bandwidth (GB/s) for "
                    "the swap cost model (default: per-device-kind table)")
    ap.add_argument("--tp", type=int, default=1,
                    help="serve mode: tensor-parallel devices — the model "
                    "shards under the Megatron rules and the paged KV "
                    "pool splits its head dimension over a tp mesh "
                    "(make_mesh); the row reports tokens/s/chip and "
                    "records devices/tp in detail")
    ap.add_argument("--pp", type=int, default=1,
                    help="serve mode: pipeline-parallel stages — the "
                    "layers split over a recurrent ring (stage_layers) "
                    "with a per-stage paged-pool shard each; composes "
                    "with --tp (tp x pp devices).  Decode lanes fill the "
                    "ring: keep --batch >= --pp or the row reports the "
                    "bubble fraction it idles (detail.pipeline)")
    ap.add_argument("--no-double-buffer", action="store_true",
                    help="serve mode: disable overlapping chunk N's host "
                    "read with chunk N+1's compute")
    ap.add_argument("--train-steps", type=int, default=6,
                    help="train mode: timed optimizer steps (after 1 warmup)")
    ap.add_argument(
        "--train-flash", choices=("auto", "on", "off"), default="auto",
        help="train mode: force the flash-attention training path on/off "
        "(auto = Trainer's backend/seq-len gate)",
    )
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="direct mode: wrap the timed run in a jax.profiler trace")
    ap.add_argument("--no-preflight", action="store_true",
                    help="downgrade a failing mdi-audit preflight to a warning "
                    "instead of refusing to launch the row")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget for the preflight audit "
                    "(default: no budget check, structural checks only)")
    ap.add_argument("--scan-unroll", type=int, default=1,
                    help="layer-scan unroll factor (single-chip engine): "
                    "trades compile time for per-layer loop overhead")
    return ap


# ---------------------------------------------------------------------------
# Direct mode (one in-process measurement)
# ---------------------------------------------------------------------------


def _serve_config(args, cfg, kv_dtype=..., tier="on"):
    """THE ServingConfig a serve row runs — preflight, warmup engine and
    timed engine all read this one builder so they can never disagree.

    --kv-dtype int8 selects the quantized pool (ServingConfig.kv_dtype);
    --serve-pool-mib converts a byte budget into max_blocks through the
    itemized `ServingConfig.block_bytes` (payload + int8 scale arrays), so
    an fp and an int8 row at the same budget hold the same pool BYTES and
    differ only in how many blocks those bytes buy (pass `kv_dtype=None`
    to build the fp twin of an int8 row at the same budget).

    `tier` builds the host-tier variants of one row: "on" (the flagged
    tier, prefix spill included), "swap" (same slabs, spill off) and
    "off" (recompute-only — host_pool_mib forced 0) — the three-way
    head-to-head the serving-cb-tiered suite row records."""
    from mdi_llm_tpu.config import ServingConfig

    if kv_dtype is ...:
        kv_dtype = "int8" if args.kv_dtype == "int8" else None
    host_mib = getattr(args, "serve_host_pool_mib", 0)
    sv = ServingConfig(
        block_size=args.serve_block_size,
        max_batch=args.batch,
        prefill_chunk=min(128, args.seq_len // 2),
        decode_chunk=args.serve_chunk,
        spec_k=args.spec_k,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        draft_model=args.draft_model,
        double_buffer=not args.no_double_buffer,
        token_budget=args.serve_token_budget,
        kv_dtype=kv_dtype,
        host_pool_mib=0 if tier == "off" else host_mib,
        host_link_gbps=getattr(args, "host_link_gbps", None),
        host_prefix_spill=tier == "on",
    )
    if args.serve_pool_mib is not None:
        per_block = sv.block_bytes(cfg, args.dtype)["total_bytes"]
        budget_blocks = int(args.serve_pool_mib * 2**20) // per_block
        # never exceed full coverage (extra blocks would just idle), never
        # go below the 2-block allocator minimum
        full = sv.num_pool_blocks(min(args.seq_len, cfg.block_size))
        sv.max_blocks = max(2, min(budget_blocks, full))
    return sv


def run_preflight(args, cfg, mode):
    """Static plan audit (mdi-audit) before any engine is built.

    Pure host-side analysis over abstract shapes — no device, no compile
    (the CompileGuard counters are untouched by construction).  ERROR
    findings refuse the row unless --no-preflight downgrades them to a
    warning; the returned dict is recorded as `detail.audit` so suite JSON
    tracks predicted vs. configured footprint per row.
    """
    from mdi_llm_tpu.analysis.audit import (
        audit_detail, enforce_preflight, preflight,
    )
    from mdi_llm_tpu.generation import _bucket, _run_cache_len

    seq_len = min(args.seq_len, cfg.block_size)
    serving, kv_len = None, None
    if mode == "serve":
        serving = _serve_config(args, cfg)
        # the widest live token axis of a serving dispatch is the unified
        # mixed step's static packed width (prompt lengths can't perturb it)
        act_t = serving.resolved_token_budget()
    else:
        total_max = args.prompt_len + (1 if mode == "prefill" else args.new_tokens)
        act_t = min(_bucket(args.prompt_len), seq_len)
        kv_len = _run_cache_len(seq_len, total_max, act_t)
    report = preflight(
        cfg,
        n_stages=args.pipeline or 1,
        pipeline=bool(args.pipeline) if mode == "decode" else False,
        tp=getattr(args, "tp", 1) if mode == "serve" else 1,
        pp=getattr(args, "pp", 1) if mode == "serve" else 1,
        samples_per_slot=args.samples_per_slot,
        n_samples=args.batch,
        batch=args.batch,
        seq_len=seq_len,
        kv_seq_len=kv_len,
        act_seq_len=act_t,
        dtype=args.dtype,
        cache_dtype=args.kv_dtype,
        quantize=args.quantize,
        serving=serving,
        hbm_gb=args.hbm_gb,
        origin=f"bench:{mode}",
    )
    enforce_preflight(report, "bench", allow=args.no_preflight)
    return audit_detail(report)


def run_probe():
    """Backend bring-up check: device enumeration + one tiny compiled op.
    The detail block doubles as the suite's device-provenance record:
    device_kind keys the `obs/roofline.py` peak table, and the versions
    say WHICH toolchain produced the row (the r03-r05 wedge was
    undiagnosable partly because no artifact recorded either)."""
    import jax
    import jax.numpy as jnp

    from mdi_llm_tpu.cli.doctor import _package_versions

    t0 = time.perf_counter()
    devs = jax.devices()
    x = jnp.ones((128, 128), jnp.bfloat16)
    (x @ x).block_until_ready()
    return {
        "metric": "backend probe",
        "value": round(time.perf_counter() - t0, 2),
        "unit": "s",
        "vs_baseline": 1.0,
        "detail": {
            "backend": jax.default_backend(),
            "device": str(devs[0]),
            "device_kind": getattr(devs[0], "device_kind", None),
            "device_count": len(devs),
            "versions": _package_versions(),
        },
    }


def run_train(args):
    """Timed optimizer steps on synthetic tokens: tokens/s/chip + MFU.

    The single-chip hardware validation path for the flash-attention
    training kernel (`ops/flash.py` custom_vjp): an unmeshed Trainer on
    TPU with block_size >= 2048 auto-engages flash for both the forward
    and the FA-2 recompute backward, so one green run of
    ``bench.py --direct --mode train --seq-len 2048`` IS the flash-VJP
    on-hardware proof (compare --train-flash on/off for the crossover).
    vs_baseline reports measured model-FLOPs utilization against the
    RUNNING device's bf16 peak from the `obs/roofline.py` peak table
    (the one source train and serve rows share); unknown device kinds
    (CPU fallback) fall back to the v5e peak, labelled "assumed" in
    detail.peak_source, so the flagship row stays comparable across
    rounds.
    """
    import jax
    import numpy as np

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.training import (
        Trainer, TrainingConfig, estimate_flops_per_token,
    )

    cfg = Config.from_name(args.model)
    use_flash = {"auto": None, "on": True, "off": False}[args.train_flash]
    tc = TrainingConfig(
        batch_size=args.batch,
        block_size=args.seq_len,
        grad_acc_steps=1,
        dtype=args.dtype if args.dtype != "float16" else "bfloat16",
        use_flash=use_flash,
    )
    trainer = Trainer(cfg, tc)
    rng = np.random.default_rng(0)
    toks = rng.integers(
        1, cfg.vocab_size, (args.train_steps + 1, 1, args.batch, args.seq_len + 1)
    )
    xs, ys = toks[..., :-1].astype(np.int32), toks[..., 1:].astype(np.int32)

    # train_step returns float(loss), which blocks on the jitted step's
    # outputs — so each iteration below is device-synchronized and the
    # wall clock measures completed steps, not async dispatch
    loss = trainer.train_step(xs[0], ys[0])  # compile + warmup
    _mark_warm()
    # ExitStack so an exception inside the timed loop cannot leak an open
    # profiler trace (a dangling trace wedges later jax.profiler sessions)
    with contextlib.ExitStack() as stack:
        if args.profile:
            stack.enter_context(jax.profiler.trace(args.profile))
        t0 = time.perf_counter()
        for i in range(1, args.train_steps + 1):
            loss = trainer.train_step(xs[i], ys[i])
        wall = time.perf_counter() - t0

    toks_per_step = args.batch * args.seq_len
    tps = args.train_steps * toks_per_step / wall
    flops_tok = estimate_flops_per_token(cfg, args.seq_len)
    # MFU against the RUNNING chip's peak (obs/roofline.py — the table
    # serve rows use too); unknown kinds fall back to the historical v5e
    # reference so CPU-fallback rows stay comparable, clearly labelled
    from mdi_llm_tpu.obs.roofline import (
        ASSUMED_TRAIN_PEAK_KIND, DEVICE_PEAKS, device_peaks,
    )

    kind = getattr(jax.devices()[0], "device_kind", None)
    peaks = device_peaks(kind)
    peak_source = (
        kind if peaks is not None
        else f"{ASSUMED_TRAIN_PEAK_KIND} (assumed; device kind {kind!r} "
        "not in the peak table)"
    )
    peak = (peaks or DEVICE_PEAKS[ASSUMED_TRAIN_PEAK_KIND])["bf16_tflops"] * 1e12
    mfu = tps * flops_tok / peak
    return {
        "metric": f"train tokens/sec/chip ({args.model}, B={args.batch}, "
                  f"T={args.seq_len}, flash={trainer.use_flash})",
        "value": round(tps, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu, 6),
        "detail": {
            "mfu": round(mfu, 6),
            "peak_tflops_per_s": peak / 1e12,
            "peak_source": peak_source,
            "tflops_per_s": round(tps * flops_tok / 1e12, 2),
            "steps": args.train_steps,
            "step_s": round(wall / args.train_steps, 4),
            "final_loss": round(float(loss), 4),
            "use_flash": bool(trainer.use_flash),
            "config": {
                "model": args.model, "batch": args.batch,
                "seq_len": args.seq_len, "dtype": tc.dtype,
            },
            "device": str(jax.devices()[0]),
        },
    }


def run_prefill(args):
    """Flash-vs-XLA prefill latency comparison (unchanged from r3)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.cli._common import resolve_kv_dtype
    from mdi_llm_tpu.generation import Generator

    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
             "float32": jnp.float32}[args.dtype]
    kv_dtype = resolve_kv_dtype(args.kv_dtype) or dtype
    cfg = Config.from_name(args.model)
    if args.pipeline:
        raise SystemExit("--mode prefill benches the single-chip engine; drop --pipeline")
    if args.quantize != "none":
        raise SystemExit(
            "--mode prefill compares against an f32 reference forward, "
            "which does not exist for a quantized tree; drop --quantize"
        )
    if args.prompt_len < 256:
        raise SystemExit(
            "--mode prefill needs --prompt-len >= 256 (the flash kernel "
            "only engages above the small-tile threshold)"
        )
    limit = min(args.seq_len, cfg.block_size)
    if args.prompt_len >= limit:
        raise SystemExit(
            f"--prompt-len {args.prompt_len} must leave generation room "
            f"below min(--seq-len, context window) = {limit}; positions "
            "past the RoPE cache would be garbage"
        )
    if jax.default_backend() != "tpu":
        print("warning: flash kernel needs TPU; both runs use the XLA path",
              file=sys.stderr, flush=True)
    audit = run_preflight(args, cfg, "prefill")

    params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, args.prompt_len).tolist()
               for _ in range(args.batch)]

    def best_prefill(use_flash):
        use_flash = use_flash and jax.default_backend() == "tpu"
        eng = Generator(
            cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
            use_flash=use_flash, quantize="none",
            # force the comparison at exactly --prompt-len (the engine's
            # auto threshold would silently fall back to XLA below 2k)
            flash_min_len=256,
        )
        eng.generate(prompts, 1, temperature=0.0)  # warmup
        best = float("inf")
        for _ in range(3):
            _, stats = eng.generate(prompts, 1, temperature=0.0)
            best = min(best, stats.prefill_s)
        return best

    # Numerics: the two attention implementations accumulate in different
    # orders, so bf16 token identity is not a meaningful invariant.  The
    # meaningful check: flash must be no less accurate than the XLA path
    # against an f32 reference forward (measured r3 on v5e: flash 0.0297 vs
    # xla 0.0303 rel err — statistically identical).
    batch_np = np.zeros((args.batch, args.prompt_len), np.int32)
    for i, p in enumerate(prompts):
        batch_np[i] = np.asarray(p, np.int32)

    # device-side reductions over the last <=512 prompt positions: full
    # (B, T, vocab) f32 logit tensors pulled to host would be multi-GB
    n_check = min(args.prompt_len, 512)

    def prompt_logits(run_params, run_dtype, use_flash):
        kv0 = transformer.init_kv_cache(
            cfg, args.batch, args.prompt_len, dtype=run_dtype
        )

        def fwd(pr, t, kv):
            logits, _ = transformer.forward(
                cfg, pr, t, jnp.zeros((args.batch,), jnp.int32), kv=kv,
                fresh_prefill=True,
                use_flash=use_flash and jax.default_backend() == "tpu",
            )
            return logits[:, -n_check:].astype(jnp.float32)

        return jax.jit(fwd)(run_params, jnp.asarray(batch_np), kv0)

    params_f32 = jax.tree_util.tree_map(lambda a: a.astype(jnp.float32), params)
    lg_ref = prompt_logits(params_f32, jnp.float32, False)
    del params_f32
    scale_ = max(1e-6, float(jnp.max(jnp.abs(lg_ref))))

    def check(use_flash):
        lg = prompt_logits(params, kv_dtype, use_flash)
        err = float(jnp.max(jnp.abs(lg - lg_ref))) / scale_
        return err, jnp.argmax(lg, -1)

    err_f, am_f = check(True)
    err_x, am_x = check(False)
    del lg_ref
    agree = float(jnp.mean(am_f == am_x))
    if err_f > err_x * 1.5 + 1e-3:
        raise AssertionError(f"flash prefill less accurate than XLA: {err_f} vs {err_x}")

    t_flash = best_prefill(True)
    t_xla = best_prefill(False)
    return {
        "metric": f"prefill latency ({args.model}, B={args.batch}, T={args.prompt_len})",
        "value": round(min(t_flash, t_xla) * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(t_xla / t_flash, 2),
        "detail": {
            "flash_ms": round(t_flash * 1000, 2),
            "xla_ms": round(t_xla * 1000, 2),
            "flash_speedup": round(t_xla / t_flash, 2),
            "flash_rel_err_vs_f32": round(err_f, 5),
            "xla_rel_err_vs_f32": round(err_x, 5),
            "argmax_agreement_bf16": round(agree, 5),
            "audit": audit,
            "device": str(jax.devices()[0]),
        },
    }


def _build_serving_gen(args, mode="serve"):
    """The (cfg, Generator, audit detail) a serving row runs — shared by
    the closed replay row (`run_serve`) and the open-system sweep
    (`run_serve_open`), so both measure exactly the audited plan."""
    import jax
    import jax.numpy as jnp

    from mdi_llm_tpu.cli._common import resolve_kv_dtype
    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.generation import Generator
    from mdi_llm_tpu.models import transformer

    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
             "float32": jnp.float32}[args.dtype]
    # int8 selects the QUANTIZED POOL (ServingConfig.kv_dtype via
    # _serve_config); the cache/compute dtype stays --dtype.  Float names
    # keep the dense cast-on-write route
    pool_int8 = args.kv_dtype == "int8"
    kv_dtype = dtype if pool_int8 else (resolve_kv_dtype(args.kv_dtype) or dtype)
    cfg = Config.from_name(args.model)
    if args.pipeline:
        raise SystemExit(
            f"--mode {mode} runs the tp-mesh engine; drop --pipeline"
        )
    audit = run_preflight(args, cfg, "serve")
    if args.quantize != "none":
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, init_quantized_params

        params = jax.device_put(init_quantized_params(
            cfg, mode=FLAG_TO_MODE[args.quantize], dtype=dtype
        ))
    else:
        params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    mesh = None
    pp = getattr(args, "pp", 1)
    if args.tp > 1 or pp > 1:
        from mdi_llm_tpu.parallel.mesh import make_mesh

        axes = {}
        if args.tp > 1:
            axes["tp"] = args.tp
        if pp > 1:
            axes["pp"] = pp
        mesh = make_mesh(axes)
    gen = Generator(
        cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
        mesh=mesh, scan_unroll=args.scan_unroll,
    )
    return cfg, gen, audit


def run_serve(args):
    """Continuous-batching serving throughput over the paged KV pool.

    Queues a mixed-length synthetic request trace (log-spread prompt
    lengths, spread output budgets — the workload static batching handles
    worst) into `Generator.serve()`'s engine and measures end-to-end
    tokens/s plus KV-block utilization.  Compare against the static-batch
    flagship row (`tinyllama-bf16`): the static row pads every lane to the
    longest sample and holds dead lanes to the end, while this row admits,
    retires and reuses blocks mid-batch — KV bytes/step scale with LIVE
    tokens (docs/perf.md "Serving").
    """
    import jax

    from mdi_llm_tpu.cli.serve import synthetic_trace

    pool_int8 = args.kv_dtype == "int8"
    cfg, gen, audit = _build_serving_gen(args)
    n_requests = args.serve_requests or 4 * args.batch
    serving_cfg = _serve_config(args, cfg)  # the audited config IS the
    # engine config (incl. kv_dtype + the --serve-pool-mib block cap)

    def build_engine(obs=None, serving=None):
        return gen.serve(serving=serving or serving_cfg, obs=obs)

    trace = synthetic_trace(
        n_requests, cfg.vocab_size, args.seq_len, args.new_tokens
    )
    # warmup on the FULL trace with tiny budgets: the serving executables
    # are all prompt-independent now — ONE (1, token_budget) unified mixed
    # step (no per-prompt-bucket prefill fns), the fixed (B, decode_chunk)
    # scan, and, with spec_k, the verify width — so the timed run below
    # reports zero post-warmup recompiles.  The warmup observer captures
    # each executable's XLA cost sheet (device=True, obs/device.py): the
    # AOT introspection compiles HERE, caches on the Generator, and the
    # timed engine's observer republishes the reports without lowering
    # anything — detail.device stays inside the CompileGuard contract
    from mdi_llm_tpu.obs import ServingObserver

    warm = build_engine(obs=ServingObserver(device=True))

    # trace-level preflight (mdi-ir): compile-set closure, donation
    # aliasing and IR hygiene over the EXACT executables this engine
    # dispatches — side-band abstract traces, so the jit cache, donation
    # behavior and CompileGuard counters of the real run are untouched
    from mdi_llm_tpu.analysis.ir import (
        enforce_ir_preflight, ir_detail, ir_preflight,
    )

    ir_report = ir_preflight(warm, origin=f"bench:{args.model}")
    enforce_ir_preflight(ir_report, "bench", allow=args.no_preflight)

    # buffer-liveness preflight (mdi-flow) over the same compile set:
    # donation aliasing + static peak-HBM land in detail.liveness
    from mdi_llm_tpu.analysis.liveness import (
        enforce_flow_preflight, flow_detail, flow_preflight,
    )

    flow_report = flow_preflight(warm, origin=f"bench:{args.model}")
    enforce_flow_preflight(flow_report, "bench", allow=args.no_preflight)

    for rid, prompt, new in trace:
        warm.add_request(
            rid, prompt, min(new, max(2, 2 * args.serve_chunk))
        )
    warm.run()
    # the verify/draft executables fire only when a draft actually hits —
    # a warmup trace with no echo leaves them cold and the first mid-serve
    # hit would compile inside the timed region; prime() dispatches each
    # once against the trash block (jit cache is per-Generator, so the
    # timed engine below inherits the compiles)
    warm.prime()

    # int8 rung: also run the FP engine on the SAME trace at the SAME pool
    # byte budget (its max_blocks shrink to what the bytes buy at fp width)
    # so the row itself carries the capacity comparison — tokens/s, peak
    # resident sequences, preemptions, latency percentiles, and the greedy
    # token-match rate of the quantized streams against the fp ones.  It
    # runs (and compiles) BEFORE the warm mark so the timed int8 region
    # below still reports zero post-warmup recompiles
    fp_results, fp_ref = None, None
    if pool_int8:
        sv_fp = _serve_config(args, cfg, kv_dtype=None)
        fp_warm = build_engine(obs=ServingObserver(device=True), serving=sv_fp)
        for rid, prompt, new in trace:
            fp_warm.add_request(
                rid, prompt, min(new, max(2, 2 * args.serve_chunk))
            )
        fp_warm.run()
        fp_obs = ServingObserver()
        fp_engine = build_engine(obs=fp_obs, serving=sv_fp)
        for rid, prompt, new in trace:
            fp_engine.add_request(rid, prompt, new)
        t0 = time.perf_counter()
        fp_results, fp_stats = fp_engine.run()
        fp_wall = time.perf_counter() - t0
        fp_ref = fp_stats.to_dict()
        fp_ref.update({
            "tokens_per_s": round(
                fp_stats.tokens_generated / fp_wall, 2
            ) if fp_wall else 0.0,
            "pool_blocks": fp_engine.pool.num_blocks,
            "kv_dtype": fp_engine.kv_dtype_name,
            "latency": {
                name: {k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in summ.items()}
                for name, summ in fp_obs.latency_summaries().items()
            },
        })

    # tiered rung: run the SAME preempt-heavy trace two more ways before
    # the warm mark — recompute-only (no tier) and swap-only (no prefix
    # spill) — so detail.tier carries the head-to-head and the greedy
    # token-match of swapped resumes against recomputed ones.  All three
    # variants share the Generator's jit cache (same dispatch shapes; the
    # tiered warmup above already compiled fetch/restore), so the timed
    # region below still reports zero post-warmup recompiles
    tier_head_to_head, tier_recompute_results = None, None
    tiered = getattr(args, "serve_host_pool_mib", 0) > 0
    if tiered:
        tier_head_to_head = {}
        for mode, tier in (("recompute", "off"), ("swap", "swap")):
            sv_t = _serve_config(args, cfg, tier=tier)
            t_warm = build_engine(obs=None, serving=sv_t)
            for rid, prompt, new in trace:
                t_warm.add_request(
                    rid, prompt, min(new, max(2, 2 * args.serve_chunk))
                )
            t_warm.run()
            t_engine = build_engine(obs=None, serving=sv_t)
            for rid, prompt, new in trace:
                t_engine.add_request(rid, prompt, new)
            t0 = time.perf_counter()
            t_results, t_stats = t_engine.run()
            t_wall = time.perf_counter() - t0
            tier_head_to_head[mode] = {
                "tokens_per_s": round(
                    t_stats.tokens_generated / t_wall, 2
                ) if t_wall else 0.0,
                "preemptions": t_stats.preemptions,
                "swaps_out": t_stats.swaps_out,
                "swaps_in": t_stats.swaps_in,
            }
            if mode == "recompute":
                tier_recompute_results = t_results

    # sampled-spec rung: run the SAME trace through a per-step-sampling
    # engine (spec_k=0, same temperature/top_k/top_p, same PRNG seed)
    # before the warm mark, so detail.spec carries the head-to-head —
    # tokens/s with the rejection-sampled verify amortizing host syncs
    # over accepted drafts vs one sync per chunk, plus the accept rate
    # those drafts actually achieved.  The baseline's compile set is a
    # subset of the spec engine's (same mixed/decode shapes, no verify),
    # so the timed region below still reports zero post-warmup recompiles
    spec_baseline, spec_key0 = None, None
    if args.spec_k > 0 and args.temperature != 0.0:
        spec_key0 = gen.key
        sv_base = _serve_config(args, cfg)
        sv_base.spec_k = 0
        sv_base.draft_model = None
        b_warm = build_engine(obs=None, serving=sv_base)
        for rid, prompt, new in trace:
            b_warm.add_request(
                rid, prompt, min(new, max(2, 2 * args.serve_chunk))
            )
        b_warm.run()
        gen.key = spec_key0
        b_engine = build_engine(obs=None, serving=sv_base)
        for rid, prompt, new in trace:
            b_engine.add_request(rid, prompt, new)
        t0 = time.perf_counter()
        _, b_stats = b_engine.run()
        b_wall = time.perf_counter() - t0
        spec_baseline = {
            "tokens_per_s": round(
                b_stats.tokens_generated / b_wall, 2
            ) if b_wall else 0.0,
            "host_syncs": b_stats.host_syncs,
            "tokens_generated": b_stats.tokens_generated,
        }
        gen.key = spec_key0  # the timed spec run draws the same stream

    _mark_warm()

    # observe the TIMED engine only: per-request TTFT/TPOT/E2E/queue-wait
    # percentiles ride into detail.latency (hooks fire at the engine's
    # existing sync boundaries — zero extra syncs/compiles, so the
    # CompileGuard row contract is untouched; docs/observability.md).
    # NOT device=True: the warmup observer already captured (and cached)
    # every executable report; this one only republishes them
    obs = ServingObserver()
    engine = build_engine(obs=obs)
    for rid, prompt, new in trace:
        engine.add_request(rid, prompt, new)
    with contextlib.ExitStack() as stack:
        if args.profile:
            stack.enter_context(jax.profiler.trace(args.profile))
        t0 = time.perf_counter()
        results, stats = engine.run()
        wall = time.perf_counter() - t0

    if fp_ref is not None:
        # greedy token-match rate of the quantized streams vs the fp rung
        # (longest matching prefix per request — post-divergence tokens
        # don't count, matching the test suite's drift metric)
        total_tok = match_tok = 0
        for rid, prompt, _new in trace:
            a = fp_results.get(rid, [])[len(prompt):]
            b = results.get(rid, [])[len(prompt):]
            n = 0
            while n < min(len(a), len(b)) and a[n] == b[n]:
                n += 1
            match_tok += n
            total_tok += max(len(a), 1)
        fp_ref["int8_token_match_rate"] = round(match_tok / total_tok, 4)

    n_chips = max(1, args.tp) * max(1, args.pp)
    total = stats.tokens_generated / wall if wall else 0.0
    value = total / n_chips  # tokens/s/CHIP: the cross-topology comparable
    base = baseline_for(args.model)

    # device-side block (docs/observability.md "Device-side"): the XLA
    # executable cost sheets captured at warmup, the achieved MFU/MBU
    # roofline at the run's mean context, and the analytic-vs-XLA FLOPs
    # cross-check that keeps the hand model honest
    from mdi_llm_tpu.obs import roofline as rf

    dev0 = jax.devices()[0]
    kind = getattr(dev0, "device_kind", None)
    # effective context per generated token ≈ prompt + half the generation
    ctxs = [
        len(p) + max(0, len(results.get(rid, [])) - len(p)) / 2
        for rid, p, _new in trace
    ]
    ctx_mean = int(sum(ctxs) / max(1, len(ctxs)))
    # weight streams amortize over the lanes actually live per step
    eff_batch = (
        max(1, round(stats.mixed_batch_occupancy * args.batch))
        if stats.mixed_batch_occupancy else args.batch
    )
    roof = rf.serving_roofline(
        cfg, serving_cfg, tokens_per_s=total, context=ctx_mean,
        batch=eff_batch, weight_bytes=rf.param_bytes(gen.params),
        device_kind=kind, n_chips=n_chips, dtype=args.dtype,
    )
    mixed_rep = obs.device.get(
        "mixed", (args.batch, engine.token_budget), engine.kv_dtype_name
    )
    cross = None
    if mixed_rep is not None:
        # the mixed executable computes token_budget positions, each
        # attending the full table window (the fallback gathers every
        # covered block) — that is the shape the analytic model must match
        window = engine.max_blocks_per_seq * engine.pool.block_size
        cross = rf.crosscheck_flops(
            mixed_rep,
            engine.token_budget * rf.decode_flops_per_token(cfg, window),
        )
    device_block = {
        "name": str(dev0),
        "kind": kind,
        "platform": jax.default_backend(),
        "roofline": roof,
        "executables": obs.device.to_dict(),
        "crosscheck": cross,
    }
    tp_tag = (f", tp={args.tp}" if args.tp > 1 else "") + (
        f", pp={args.pp}" if args.pp > 1 else ""
    )
    # canonical serving stats (ServingStats.to_dict — same dict mdi-serve
    # prints) + bench extras; the percentile block is the production
    # metric tokens/s alone hides (ROADMAP item 2)
    detail = stats.to_dict()
    detail.update({
        "tokens_per_s_total": round(total, 2),
        "devices": n_chips,
        "tp": args.tp,
        "pp": args.pp,
        "wall_s": round(wall, 2),  # timed region, not stats.wall_s
        "latency": {
            name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in summ.items()}
            for name, summ in obs.latency_summaries().items()
        },
        "audit": audit,
        "ir": ir_detail(ir_report),
        "liveness": flow_detail(flow_report),
        "baseline_tokens_per_s": base,
        "config": {
            "model": args.model, "slots": args.batch,
            "block_size": args.serve_block_size,
            "token_budget": engine.token_budget,  # resolved, not the flag
            "decode_chunk": args.serve_chunk, "spec_k": args.spec_k,
            "temperature": args.temperature, "top_k": args.top_k,
            "top_p": args.top_p, "draft_model": args.draft_model,
            "double_buffer": not args.no_double_buffer,
            "scan_unroll": args.scan_unroll,
            "seq_len": args.seq_len, "new_tokens": args.new_tokens,
            "requests": n_requests, "kv_dtype": engine.kv_dtype_name,
            "pool_blocks": engine.pool.num_blocks,
            "pool_mib": args.serve_pool_mib,
            "quantize": args.quantize,
        },
        "kernel": engine.kernel_info(),
        "device": device_block,
    })
    if spec_baseline is not None:
        # sampled-spec head-to-head (serving-cb-spec): the timed engine's
        # rejection-verify throughput and accept rate vs the per-step
        # sampling baseline that ran the same trace at the same seed
        drafted = stats.spec_drafted_ngram + stats.spec_drafted_model
        accepted = stats.spec_accepted_ngram + stats.spec_accepted_model
        detail["spec"] = {
            "spec_k": args.spec_k,
            "temperature": args.temperature,
            "tokens_per_s": round(total, 2),
            "drafted": drafted,
            "accepted": accepted,
            "accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
            "host_syncs": stats.host_syncs,
            "baseline": spec_baseline,
            "speedup": (
                round(total / spec_baseline["tokens_per_s"], 3)
                if spec_baseline["tokens_per_s"] else None
            ),
        }
    if args.pp > 1:
        # ring topology + fill model (serving/pipeline.py): stages, the
        # stage layer split, per-stage occupancy and the bubble fraction
        detail["pipeline"] = engine.pipeline_fill()
    if fp_ref is not None:
        detail["fp_reference"] = fp_ref
    if tiered and engine.host_tier is not None:
        # restore-hidden fraction: the host-side restore ISSUE time vs the
        # link-model estimate of the full transfer — the remainder rode
        # behind the next decode chunk's device work
        link = engine.host_tier.cost_model.link_gbps
        est_s = stats.swap_in_bytes / (link * 1e9) if link > 0 else 0.0
        hidden = (
            round(max(0.0, min(1.0, 1.0 - stats.restore_issue_s / est_s)), 4)
            if est_s > 0 else None
        )
        match_tok = total_tok = 0
        if tier_recompute_results is not None:
            # greedy token-identity of swapped resumes vs recompute — the
            # tier's correctness contract, banked in the row itself
            for rid, prompt, _new in trace:
                a = tier_recompute_results.get(rid, [])[len(prompt):]
                b = results.get(rid, [])[len(prompt):]
                n = 0
                while n < min(len(a), len(b)) and a[n] == b[n]:
                    n += 1
                match_tok += n
                total_tok += max(len(a), 1)
        tier_head_to_head["swap_spill"] = {
            "tokens_per_s": round(total, 2),
            "preemptions": stats.preemptions,
            "swaps_out": stats.swaps_out,
            "swaps_in": stats.swaps_in,
        }
        detail["tier"] = {
            "host_pool_mib": args.serve_host_pool_mib,
            "host_blocks": engine.host_tier.store.num_slots,
            "host_link_gbps": link,
            "swap_out_bytes": stats.swap_out_bytes,
            "swap_in_bytes": stats.swap_in_bytes,
            "swaps_out": stats.swaps_out,
            "swaps_in": stats.swaps_in,
            "prefix_hits_host": stats.prefix_hits_host,
            "restore_issue_s": round(stats.restore_issue_s, 4),
            "restore_hidden_fraction": hidden,
            "swap_token_match_rate": (
                round(match_tok / total_tok, 4) if total_tok else None
            ),
            "head_to_head": tier_head_to_head,
        }
    return {
        "metric": f"serving tokens/sec/chip ({args.model}, cb, "
                  f"slots={args.batch}, reqs={n_requests}{tp_tag})",
        "value": round(value, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(value / base, 2),
        "detail": detail,
    }


def run_serve_open(args):
    """Open-system serving: max QPS under a p99 TTFT/TPOT SLO.

    The closed `serve` row measures throughput with the whole trace
    queued at t=0; production traffic arrives continuously, and the
    number an open system is judged on is the highest OFFERED load whose
    tail latency still meets the SLO.  This row runs the real subsystem
    end to end — `server/frontend.py`'s engine thread + bounded admission
    channel fed by Poisson arrivals (`server/loadgen.py`) — and sweeps
    offered QPS ascending until p99 TTFT or TPOT breaks the --slo-*
    ceilings (or arrivals get 429-shed: a sweep point that rejects load
    fails its SLO by definition).  The reported value is the max passing
    QPS; every sweep point's full latency block + canonical serving stats
    land in detail.sweep.

    Default grid: a closed replay first calibrates service capacity
    (requests/s at saturation), then the sweep brackets it at
    [0.25, 0.5, 0.75, 1.0, 1.25]x — so the knee lands inside the grid on
    any backend speed without hand-tuning."""
    import jax

    from mdi_llm_tpu.cli.serve import synthetic_trace
    from mdi_llm_tpu.obs import ServingObserver
    from mdi_llm_tpu.server import (
        OpenLoopRunner,
        ServingFrontend,
        poisson_arrivals,
        sweep_offered_load,
    )

    cfg, gen, audit = _build_serving_gen(args, mode="serve-open")
    serving_cfg = _serve_config(args, cfg)
    n_requests = args.serve_open_requests or 3 * args.batch
    trace = synthetic_trace(
        n_requests, cfg.vocab_size, args.seq_len, args.new_tokens
    )

    # warmup exactly like the closed serve row: the front-end adds
    # threads AROUND the engine loop, never inside it, so the executable
    # set is identical and the sweep below runs zero post-warmup
    # recompiles (detail.compiles records it)
    warm = gen.serve(serving=serving_cfg, obs=ServingObserver(device=True))

    from mdi_llm_tpu.analysis.ir import (
        enforce_ir_preflight, ir_detail, ir_preflight,
    )

    ir_report = ir_preflight(warm, origin=f"bench:{args.model}")
    enforce_ir_preflight(ir_report, "bench", allow=args.no_preflight)

    from mdi_llm_tpu.analysis.liveness import (
        enforce_flow_preflight, flow_detail, flow_preflight,
    )

    flow_report = flow_preflight(warm, origin=f"bench:{args.model}")
    enforce_flow_preflight(flow_report, "bench", allow=args.no_preflight)
    for rid, prompt, new in trace:
        warm.add_request(rid, prompt, min(new, max(2, 2 * args.serve_chunk)))
    warm.run()

    # closed-replay calibration: service capacity in requests/s sizes the
    # auto grid (skipped when --serve-open-qps pins the grid explicitly)
    if args.serve_open_qps:
        grid = sorted(float(q) for q in args.serve_open_qps.split(","))
        cal = None
    else:
        cal_engine = gen.serve(serving=serving_cfg)
        for rid, prompt, new in trace:
            cal_engine.add_request(rid, prompt, new)
        t0 = time.perf_counter()
        cal_engine.run()
        cal_wall = max(time.perf_counter() - t0, 1e-6)
        cap_qps = n_requests / cal_wall
        cal = {"wall_s": round(cal_wall, 3),
               "capacity_qps": round(cap_qps, 3)}
        grid = [round(cap_qps * f, 3) for f in (0.25, 0.5, 0.75, 1.0, 1.25)]

    _mark_warm()

    slo = {"ttft_p99_s": args.slo_ttft_ms / 1e3,
           "tpot_p99_s": args.slo_tpot_ms / 1e3}
    points = {}  # qps -> (stats, latency block) for the detail

    def measure(qps):
        # fresh engine + observer per point (compiled fns shared via the
        # Generator's serve-fn cache — nothing recompiles), real wall
        # clock: an open loop cannot be faked onto a virtual clock
        # without faking the service process too
        obs = ServingObserver()
        engine = gen.serve(serving=serving_cfg, obs=obs)
        frontend = ServingFrontend(engine)
        frontend.start()
        arrivals = poisson_arrivals(trace, qps)
        rep = OpenLoopRunner(frontend, arrivals).run()
        frontend.drain(timeout=600.0)
        frontend.stop()
        lat = obs.latency_summaries()
        stats = engine.stats
        points[qps] = {
            "stats": stats.to_dict(),
            "latency": {
                name: {k: (round(v, 6) if isinstance(v, float) else v)
                       for k, v in summ.items()}
                for name, summ in lat.items()
            },
            "open_loop": rep.to_dict(),
        }
        return {
            "ttft_p99_s": lat["ttft_s"].get("p99"),
            "tpot_p99_s": lat["tpot_s"].get("p99"),
            "rejected": rep.rejected,
            "completed": rep.completed,
            "offered_qps": round(rep.offered_qps, 3),
            "tokens_per_s": stats.to_dict()["tokens_per_s"],
        }

    sweep = sweep_offered_load(measure, grid, slo)
    for row in sweep["rows"]:
        row.update(points.get(row["qps"], {}))
    max_ok = sweep["max_qps_ok"]
    # the point whose latency/device detail headlines: the best passing
    # one, else the first measured (the knee diagnosis still needs data)
    head = points.get(max_ok) or (points[sweep["rows"][0]["qps"]]
                                  if sweep["rows"] else {})

    dev0 = jax.devices()[0]
    device_block = {
        "name": str(dev0),
        "kind": getattr(dev0, "device_kind", None),
        "platform": jax.default_backend(),
        # executable cost sheets captured at warmup (obs/device.py) —
        # the sweep observers republish nothing new
        "executables": len(gen._exec_reports),
    }
    return {
        "metric": f"serving max QPS @ SLO (ttft p99 <= {args.slo_ttft_ms:g}"
                  f"ms, tpot p99 <= {args.slo_tpot_ms:g}ms; {args.model}, "
                  f"slots={args.batch}, open-loop poisson)",
        "value": round(max_ok, 3) if max_ok is not None else 0.0,
        # vs_baseline: fraction of the swept ceiling sustained under SLO
        # (1.0 = even the top of the grid passed; the knee is beyond it)
        "vs_baseline": round((max_ok or 0.0) / grid[-1], 2),
        "unit": "req/s@slo",
        "detail": {
            "slo": slo,
            "arrivals": "poisson",
            "requests_per_point": n_requests,
            "qps_grid": grid,
            "calibration": cal,
            "max_qps_ok": max_ok,
            "knee_qps": sweep["knee_qps"],
            "sweep": sweep["rows"],
            "latency": head.get("latency"),
            "stats": head.get("stats"),
            "audit": audit,
            "ir": ir_detail(ir_report),
            "liveness": flow_detail(flow_report),
            "device": device_block,
            "config": {
                "model": args.model, "slots": args.batch,
                "block_size": args.serve_block_size,
                "decode_chunk": args.serve_chunk,
                "seq_len": args.seq_len, "new_tokens": args.new_tokens,
                "kv_dtype": warm.kv_dtype_name,
                "admission_queue": serving_cfg.resolved_admission_queue(),
            },
            "kernel": warm.kernel_info(),
        },
    }


def run_kernel(args):
    """Paged-attention kernel microbench (ROADMAP item 4's measurement
    substrate): time the Pallas kernel vs the gather fallback vs dense
    attention for the three serving dispatch shapes — decode (Tq=1),
    ragged speculative verify (Tq=8), and the unified ragged mixed
    prefill — at BOTH pool dtypes (fp and int8), so the in-kernel dequant
    cost is measured, not asserted.  Kernel timings need a TPU backend
    (the interpreter measures nothing); fallback and dense run anywhere,
    so a CPU row still banks the dtype comparison for those paths."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.ops.attention import multihead_attention
    from mdi_llm_tpu.ops.paged_attention import paged_attention, paged_prefill
    from mdi_llm_tpu.ops.tuning import DEFAULT_PARAMS, resolve_kernel_params

    cfg = Config.from_name(args.model)
    H, G, hs = cfg.n_head, cfg.n_query_groups, cfg.head_size
    B = min(args.batch, 8)
    BS = args.serve_block_size
    S = min(args.seq_len, 1024)
    S -= S % BS
    MB = S // BS
    NB = 1 + B * MB
    Tq = 8  # the spec_k=7 verify width
    Tpk = 2 * B  # packed mixed step: B decode lanes + one B-token chunk
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)
    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
             "float32": jnp.float32}[args.dtype]

    kf = rng.standard_normal((NB, BS, G, hs)).astype(np.float32)
    vf = rng.standard_normal((NB, BS, G, hs)).astype(np.float32)
    pool_fp = (jnp.asarray(kf, dtype), jnp.asarray(vf, dtype))

    def quantize(arr):  # per-block-per-group symmetric int8, the pool layout
        scale = np.abs(arr).max(axis=(1, 3)) / 127.0  # (NB, G)
        safe = np.where(scale > 0, scale, 1.0)
        q = np.clip(np.round(arr / safe[:, None, :, None]), -127, 127)
        return {"q": jnp.asarray(q, jnp.int8),
                "scale": jnp.asarray(scale, jnp.float32)}

    pool_q8 = (quantize(kf), quantize(vf))
    tables = jnp.asarray(
        np.arange(1, NB).reshape(B, MB), jnp.int32
    )
    k_dense = jnp.asarray(
        kf.reshape(NB, BS, G, hs)[np.asarray(tables).reshape(-1)]
        .reshape(B, S, G, hs).transpose(0, 2, 1, 3), dtype
    )
    v_dense = jnp.asarray(
        vf.reshape(NB, BS, G, hs)[np.asarray(tables).reshape(-1)]
        .reshape(B, S, G, hs).transpose(0, 2, 1, 3), dtype
    )

    def timed(fn, *xs, reps=20):
        out = fn(*xs)  # compile + warm
        jax.block_until_ready(out)
        jax.block_until_ready(fn(*xs))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*xs)
        jax.block_until_ready(out)
        return round((time.perf_counter() - t0) / reps * 1e6, 1)  # µs

    q1 = jnp.asarray(rng.standard_normal((B, H, 1, hs)), dtype)
    pos1 = jnp.full((B, 1), S - 1, jnp.int32)
    qr = jnp.asarray(rng.standard_normal((B, H, Tq, hs)), dtype)
    posr = jnp.asarray(
        np.broadcast_to(np.arange(S - Tq, S), (B, Tq)).copy(), jnp.int32
    )
    qp = jnp.asarray(rng.standard_normal((1, H, Tpk, hs)), dtype)
    q_slot = jnp.asarray(np.repeat(np.arange(B), 2), jnp.int32)
    q_start = jnp.asarray(np.arange(B) * 2, jnp.int32)
    q_len = jnp.full((B,), 2, jnp.int32)
    posp = jnp.asarray(np.tile([S - 2, S - 1], B), jnp.int32)

    def attn(pools, use_kernel, params=None):
        k_pool, v_pool = pools
        return {
            "decode": lambda: timed(jax.jit(partial(
                paged_attention, use_kernel=use_kernel, params=params,
            )), q1, k_pool, v_pool, tables, pos1),
            "ragged": lambda: timed(jax.jit(partial(
                paged_attention, use_kernel=use_kernel, params=params,
            )), qr, k_pool, v_pool, tables, posr),
            "prefill": lambda: timed(
                jax.jit(lambda q, kp, vp, t: paged_prefill(
                    q, kp, vp, t, q_slot, q_start, q_len, posp,
                    use_kernel=use_kernel, params=params,
                )), qp, k_pool, v_pool, tables,
            ),
        }

    dense_fns = {
        "decode": lambda: timed(
            jax.jit(multihead_attention), q1, k_dense, v_dense, pos1
        ),
        "ragged": lambda: timed(
            jax.jit(multihead_attention), qr, k_dense, v_dense, posr
        ),
        # dense comparison for the mixed step: the same (head, token)
        # rows as B lanes of 2 queries over the full contiguous window
        "prefill": lambda: timed(
            jax.jit(multihead_attention),
            qp.reshape(1, H, B, 2, hs)[0].transpose(1, 0, 2, 3),
            k_dense, v_dense,
            posp.reshape(B, 2),
        ),
    }

    device_kind = jax.devices()[0].device_kind if on_tpu else None
    tuning = {}
    for tag in ("fp", "int8"):
        params, meta = resolve_kernel_params(
            n_head=H, n_groups=G, head_size=hs, block_size=BS,
            kv_dtype="int8" if tag == "int8" else None,
            device_kind=device_kind,
        )
        tuning[tag] = {
            "tuned": meta["tuned"], "table_source": meta["table_source"],
            "key": meta["key"], "params": params.to_dict(),
            "default_params": DEFAULT_PARAMS.to_dict(),
            "_resolved": params,
        }

    grid = {}
    for tag, pools in (("fp", pool_fp), ("int8", pool_q8)):
        tuned_params = tuning[tag]["_resolved"]
        for op in ("decode", "ragged", "prefill"):
            row = {
                "fallback_us": attn(pools, False)[op](),
                "dense_us": dense_fns[op]() if tag == "fp" else None,
                "kernel_us": (
                    attn(pools, True, tuned_params)[op]() if on_tpu else None
                ),
                "kernel_default_us": (
                    attn(pools, True, DEFAULT_PARAMS)[op]()
                    if on_tpu and tuning[tag]["tuned"] else None
                ),
            }
            if row["kernel_us"] and row["kernel_default_us"]:
                row["tuned_speedup"] = round(
                    row["kernel_default_us"] / row["kernel_us"], 3
                )
            grid[f"{op}-{tag}"] = row
    for tag in tuning:
        del tuning[tag]["_resolved"]
    _mark_warm()

    value = grid["decode-fp"]["kernel_us"] or grid["decode-fp"]["fallback_us"]
    return {
        "metric": (
            f"paged-attention decode µs/dispatch ({args.model}, B={B}, "
            f"S={S}, {'kernel' if on_tpu else 'fallback'})"
        ),
        "value": value,
        "unit": "us",
        "vs_baseline": 1.0,
        "detail": {
            "grid": grid,
            "tuning": tuning,
            "shapes": {
                "batch": B, "seq": S, "block_size": BS, "heads": H,
                "groups": G, "head_size": hs, "ragged_tq": Tq,
                "packed_tokens": Tpk, "dtype": args.dtype,
            },
            "kernel_backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
    }


def run_decode(args):
    """Batched (or pipeline-ring) decode throughput measurement."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.models import transformer
    from mdi_llm_tpu.cli._common import resolve_kv_dtype

    dtype = {"bfloat16": jnp.bfloat16, "float16": jnp.float16,
             "float32": jnp.float32}[args.dtype]
    kv_dtype = resolve_kv_dtype(args.kv_dtype) or dtype
    cfg = Config.from_name(args.model)
    audit = run_preflight(args, cfg, "decode")
    if args.quantize != "none":
        # build the int8/int4 tree directly: an 8B-class model never exists
        # in f32/bf16, so Llama-3-8B fits one v5e chip for quantized benches
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, init_quantized_params

        params = init_quantized_params(
            cfg, mode=FLAG_TO_MODE[args.quantize], dtype=dtype
        )
        if not args.pipeline:
            # single-chip engine keeps the tree as-is: pin it on device once
            # (PipelineEngine re-splits host-side and places per stage)
            params = jax.device_put(params)
        quantize = "none"  # engines receive pre-quantized params
    else:
        params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        quantize = args.quantize
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, args.prompt_len).tolist()
        for _ in range(args.batch)
    ]

    if args.pipeline:
        from mdi_llm_tpu.parallel.pipeline import PipelineEngine

        engine = PipelineEngine(
            cfg,
            params,
            n_stages=args.pipeline,
            max_seq_length=args.seq_len,
            cache_dtype=kv_dtype,
            quantize=quantize,
            samples_per_slot=args.samples_per_slot,
            rotations_per_call=args.chunk,
        )
        label = f"pipeline{args.pipeline}" + (
            f"xM{args.samples_per_slot}" if args.samples_per_slot > 1 else ""
        ) + (f"+{args.quantize}" if args.quantize != "none" else "")
    else:
        from mdi_llm_tpu.generation import Generator

        engine = Generator(
            cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
            quantize=quantize, scan_unroll=args.scan_unroll,
        )
        label = "batched-decode" + (
            f"+{args.quantize}" if args.quantize != "none" else ""
        ) + (f"+unroll{args.scan_unroll}" if args.scan_unroll != 1 else "")

    kwargs = {} if args.pipeline else {"chunk_size": args.chunk}
    # warmup with the run's own token budget: KV caches are sized to the run
    # (prompt+max_new bucket), so a shorter warmup would compile a different
    # cache shape and the timed run would recompile inside the measurement
    engine.generate(prompts, args.new_tokens, temperature=0.0, **kwargs)
    _mark_warm()  # the timed region below must not compile ANYTHING
    # ExitStack: see run_train — no leaked profiler trace on a failed run
    with contextlib.ExitStack() as stack:
        if args.profile:
            stack.enter_context(jax.profiler.trace(args.profile))
        t0 = time.perf_counter()
        outs, stats = engine.generate(
            prompts, args.new_tokens, temperature=0.0, **kwargs
        )
        wall = time.perf_counter() - t0

    toks = sum(len(o) - args.prompt_len for o in outs)
    decode_tps = stats.tokens_generated / stats.decode_s if stats.decode_s else 0.0
    n_chips = max(1, args.pipeline)
    value = decode_tps / n_chips
    base = baseline_for(args.model)

    return {
        "metric": f"decode tokens/sec/chip ({args.model}, B={args.batch}, {label})",
        "value": round(value, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(value / base, 2),
        "detail": {
            "total_tokens": toks,
            "decode_tokens_per_s": round(decode_tps, 2),
            "prefill_s": round(stats.prefill_s, 3),
            "wall_s": round(wall, 2),
            "audit": audit,
            "baseline_tokens_per_s": base,
            "config": {
                "model": args.model, "batch": args.batch, "chunk": args.chunk,
                "quantize": args.quantize, "kv_dtype": args.kv_dtype,
                "seq_len": args.seq_len, "new_tokens": args.new_tokens,
                "pipeline": args.pipeline,
                "samples_per_slot": args.samples_per_slot,
            },
            "device": str(jax.devices()[0]),
        },
    }


def _enable_compile_cache():
    """Point JAX at an on-disk compilation cache next to this file.

    Over the remote-compile tunnel a cold Llama-3-8B compile costs ~15 min
    (r5 suite: the int8 row returned at t=1150 s, almost all of it compile);
    a cached executable loads in seconds.  Because the cache lives in the
    repo tree, any manual `--direct` sweep pre-warms the driver's official
    end-of-round suite run.  Opt out with MDI_JAX_CACHE=off (the cache is
    keyed on HLO + compiler version, so staleness is safe, not wrong).
    """
    cache_dir = os.environ.get(
        "MDI_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    if cache_dir == "off":
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as exc:  # cache is an optimization, never a failure
        print(f"bench: compile cache unavailable: {exc}", file=sys.stderr)


def run_direct(args):
    global _GUARD
    if args.backend == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    if args.chunk is None:
        args.chunk = 16 if args.pipeline else 256
    from mdi_llm_tpu.utils.profiling import CompileGuard

    _GUARD = CompileGuard(label=f"bench:{'probe' if args.probe else args.mode}")
    try:
        with _GUARD:
            if args.probe:
                out = run_probe()
            elif args.mode == "prefill":
                out = run_prefill(args)
            elif args.mode == "train":
                if args.pipeline:
                    raise SystemExit(
                        "--mode train benches the unmeshed Trainer; drop --pipeline"
                    )
                out = run_train(args)
            elif args.mode == "serve":
                out = run_serve(args)
            elif args.mode == "serve-open":
                out = run_serve_open(args)
            elif args.mode == "kernel":
                out = run_kernel(args)
            else:
                out = run_decode(args)
        out.setdefault("detail", {})["compiles"] = _GUARD.summary()
        if args.mode == "decode" and not args.probe:
            # the steady-state contract: a timed decode region that traces
            # even once is measuring compiles, not tokens — fail the row
            # loudly (RecompileError) rather than record a poisoned number
            _GUARD.expect_clean()
        return out
    finally:
        _GUARD = None


# ---------------------------------------------------------------------------
# Suite mode (orchestrator)
# ---------------------------------------------------------------------------

# Each row: name, child argv tail, per-attempt timeout, and a fallback
# ladder of flag overrides walked on (non-backend) failure.  Ordered so the
# safest/most valuable rows run first: if a later aggressive config wedges
# the tunnel backend, the recorded artifact already holds the earlier rows.
SUITE_ROWS = [
    {
        "name": "tinyllama-bf16",
        "headline": True,
        "flags": ["--batch", "24", "--chunk", "256", "--new-tokens", "512"],
        "ladder": [["--batch", "16"], ["--batch", "8", "--chunk", "128"]],
        "timeout": 900,
    },
    {  # BASELINE.md north star: Llama-3-8B-Instruct single-chip decode
        "name": "llama3-8b-int8",
        "flags": ["--model", "Llama-3-8B-Instruct", "--quantize", "int8",
                   "--batch", "8", "--seq-len", "512", "--new-tokens", "256"],
        "ladder": [["--batch", "4"]],
        "timeout": 1200,
    },
    {  # second north-star row: int4 halves the weight bytes again
        "name": "llama3-8b-int4",
        "flags": ["--model", "Llama-3-8B-Instruct", "--quantize", "int4",
                   "--batch", "8", "--seq-len", "512", "--new-tokens", "256"],
        "ladder": [["--batch", "4"]],
        "timeout": 1200,
    },
    {  # HBM-roof push: int8 MXU matmuls at the proven batch (B=32's
        # compile wedged the tunnel backend in r3 — never re-run it here)
        "name": "tinyllama-w8a8",
        "flags": ["--quantize", "w8a8", "--batch", "24", "--chunk", "256",
                   "--new-tokens", "512"],
        "ladder": [["--batch", "16"]],
        "timeout": 900,
    },
    {  # continuous-batching serving over the paged KV pool vs the static
        # flagship row above: mixed-length trace, mid-batch admit/retire,
        # tokens/s + KV-block utilization in detail.  Decode runs the
        # multi-token serving step (decode_chunk=8 scan, double-buffered),
        # so detail reports tokens_per_sync >= 8; the ladder rung drops to
        # the per-step engine if the chunked graph fails to build
        "name": "serving-cb",
        "flags": ["--mode", "serve", "--batch", "8", "--seq-len", "512",
                   "--new-tokens", "128"],
        "ladder": [["--serve-chunk", "1"],
                   ["--batch", "4", "--new-tokens", "64"]],
        "timeout": 900,
    },
    {  # the first MULTI-CHIP serving row: the same cb trace with the model
        # Megatron-sharded and the paged pool's KV-group axis split over a
        # tp mesh (unit stays tokens/s/chip; detail records devices/tp and
        # the total).  tp=4 is TinyLlama's max shardable degree
        # (n_query_groups=4); the ladder drops to tp=2, then the
        # single-chip engine, so a collective/mesh failure still records a
        # serving row
        "name": "serving-cb-tp4",
        "flags": ["--mode", "serve", "--tp", "4", "--batch", "8",
                   "--seq-len", "512", "--new-tokens", "128"],
        "ladder": [["--tp", "2"], ["--tp", "1"]],
        "timeout": 1200,
    },
    {  # the PIPELINED serving row: the same cb trace with the layers
        # split over a 2-stage recurrent ring (serving/pipeline.py), each
        # stage holding its own paged-pool shard; decode lanes fill the
        # ring (batch=8 >= pp=2, zero steady-state bubbles).  Unit stays
        # tokens/s/chip; detail.pipeline records stages, the stage layer
        # split, per-stage occupancy and the bubble fraction.  The ladder
        # drops to the single-chip engine so a ring/mesh failure still
        # records a serving row
        "name": "serving-cb-pp2",
        "flags": ["--mode", "serve", "--pp", "2", "--batch", "8",
                   "--seq-len", "512", "--new-tokens", "128"],
        "ladder": [["--pp", "1"]],
        "timeout": 1200,
    },
    {  # the quantized-pool rung: the SAME cb trace with the paged pool
        # stored int8 (per-block scales, in-kernel dequant) at a FIXED
        # pool byte budget — the row itself also runs the fp engine at
        # that byte budget and records the capacity comparison in
        # detail.fp_reference (pool_blocks ~2x, resident_peak,
        # preemptions, TTFT/TPOT percentiles, int8_token_match_rate).
        # The ladder drops the budget cap, then falls back to the fp pool
        # so an int8-path failure still records a serving row
        "name": "serving-cb-int8",
        "flags": ["--mode", "serve", "--batch", "8", "--seq-len", "512",
                   "--new-tokens", "128", "--kv-dtype", "int8",
                   "--serve-pool-mib", "24"],
        "ladder": [["--serve-pool-mib", "48"], ["--kv-dtype", "auto"]],
        "timeout": 900,
    },
    {  # the TIERED-KV rung: the cb trace over a pool capped small enough
        # to thrash (sustained preemption) with a host-RAM block tier
        # under it — preemption victims swap their blocks to pinned host
        # slabs and resume without re-prefill, cold prefix chains spill
        # there instead of dropping.  The row runs the SAME trace three
        # ways (recompute-only / swap / swap+prefix-spill) and banks the
        # head-to-head tokens/s, swap bytes, the restore-hidden fraction
        # and the swap-vs-recompute greedy token-match rate in
        # detail.tier.  The ladder relaxes the thrash cap, then drops the
        # tier so a host-tier failure still records a serving row
        "name": "serving-cb-tiered",
        "flags": ["--mode", "serve", "--batch", "8", "--seq-len", "512",
                   "--new-tokens", "128", "--serve-pool-mib", "48",
                   "--serve-host-pool-mib", "256"],
        "ladder": [["--serve-pool-mib", "96"],
                   ["--serve-host-pool-mib", "0"]],
        "timeout": 1200,
    },
    {  # the SAMPLED-SPECULATIVE rung: the cb trace at temperature>0 with
        # the rejection-sampled verify over n-gram drafts, head-to-head
        # against the SAME trace through per-step sampling (spec_k=0) at
        # the same PRNG seed — detail.spec banks both tokens/s, the
        # accept rate the drafts achieved, and the host-sync counts the
        # speedup comes from.  top_k=1 keeps the sampled stream
        # deterministic so the n-gram drafter reliably fires on a
        # random-init model (broader filters leave drafts workload-
        # dependent: real weights echo, random ones may not); the ladder
        # drops spec entirely so a verify-path failure still records a
        # sampling serving row
        "name": "serving-cb-spec",
        "flags": ["--mode", "serve", "--batch", "8", "--seq-len", "512",
                   "--new-tokens", "128", "--spec-k", "4",
                   "--temperature", "0.7", "--top-k", "1"],
        "ladder": [["--spec-k", "0", "--temperature", "0.7"]],
        "timeout": 900,
    },
    {  # the OPEN-SYSTEM serving row (ROADMAP item 1's headline): Poisson
        # arrivals through the async front-end sweep offered load for the
        # max QPS meeting the p99 TTFT/TPOT SLO — the number every
        # "serves production traffic" claim reduces to.  The auto grid
        # self-calibrates off a closed replay, so the same flags land the
        # knee on any backend; the ladder shrinks the point size if the
        # full sweep can't fit the row timeout
        "name": "serving-open",
        "flags": ["--mode", "serve-open", "--batch", "8", "--seq-len",
                   "512", "--new-tokens", "64", "--serve-open-requests",
                   "24"],
        "ladder": [["--serve-open-requests", "12", "--new-tokens", "32"],
                   ["--batch", "4", "--serve-open-requests", "8",
                    "--new-tokens", "16"]],
        "timeout": 1200,
    },
    {  # paged-attention kernel microbench (ROADMAP item 4's measurement
        # substrate): Pallas kernel vs gather fallback vs dense attention
        # for decode/ragged-verify/ragged-prefill at fp AND int8 — the
        # in-kernel dequant cost lands in detail.grid as data, not as an
        # assertion.  Kernel timings need the TPU backend; a CPU fallback
        # run still banks the fallback/dense dtype comparison
        "name": "kernel-paged",
        "flags": ["--mode", "kernel", "--batch", "8", "--seq-len", "1024"],
        "ladder": [["--batch", "4", "--seq-len", "512"]],
        "timeout": 900,
    },
    {  # flash-VJP training on hardware: --train-flash on forces the Pallas
        # custom_vjp (fails loudly if it cannot engage, e.g. a backend whose
        # default_backend() string defeats the Trainer's auto gate); the
        # ladder rung falls back to the auto gate so a kernel-path failure
        # still records a training-MFU row (detail.use_flash says which ran;
        # vs_baseline = fraction of the v5e bf16 peak).
        "name": "tinyllama-train-2k",
        "flags": ["--mode", "train", "--batch", "4", "--seq-len", "2048",
                   "--train-steps", "4", "--train-flash", "on"],
        "ladder": [["--train-flash", "auto"], ["--batch", "2"]],
        "timeout": 1500,
    },
    {  # recurrent ring on one chip (the reference's headline execution
        # model).  LAST because it is the costliest compile in the suite:
        # its r5 cold compile blew a 900 s timeout on the tunnel backend,
        # and a timeout kill mid-compile is the known wedge trigger — any
        # row after it would be skipped.  seq-len 512 + 128 new tokens keep
        # the graph as small as the story allows; the compile cache makes
        # re-runs cheap once one compile has ever finished.
        "name": "ring-pipeline-m16",
        "flags": ["--pipeline", "1", "--samples-per-slot", "16",
                   "--batch", "16", "--seq-len", "512", "--new-tokens", "128"],
        "ladder": [["--samples-per-slot", "8", "--batch", "8"]],
        "timeout": 1500,
    },
]

BACKEND_ERR = "Unable to initialize backend"
# the r03–r05 probe-wedge signature: libtpu's bring-up queries the GCE
# instance metadata server for each tpu-env variable and retries EVERY
# 403/failure 30 times (~30 s+ per variable, several variables), so on a
# host without working TPU metadata a single probe burns minutes before
# concluding anything — the probe budget expires first and the suite
# falls back to CPU even when diagnosis would have been instant
_MDS_WEDGE_SIGNATURE = "Failed to get TPU metadata"


def _tpu_hardware_evidence():
    """Host-local evidence that a TPU could exist here — WITHOUT touching
    libtpu (whose bring-up is exactly the thing that wedges).  Checks the
    accelerator device nodes a mounted TPU exposes and the env vars every
    TPU runtime (GCE VM, tunnel plugin, colab) sets.  All reads are local
    filesystem/env: microseconds, cannot hang."""
    import glob

    evidence = {
        "dev_accel": sorted(glob.glob("/dev/accel*")),
        "dev_vfio": sorted(glob.glob("/dev/vfio/*")),
        "env": {
            k: os.environ[k]
            for k in ("TPU_NAME", "TPU_ACCELERATOR_TYPE", "TPU_WORKER_ID",
                      "COLAB_TPU_ADDR", "MDI_FORCE_TPU_PROBE")
            if k in os.environ
        },
    }
    evidence["present"] = bool(
        evidence["dev_accel"] or evidence["dev_vfio"] or evidence["env"]
    )
    return evidence


def _child(argv_tail, timeout, env=None):
    """Run one measurement in a fresh interpreter.  Returns (dict|None, err)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--direct"] + argv_tail
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env={**os.environ, **(env or {})},
        )
    except subprocess.TimeoutExpired as e:
        # keep whatever stderr the child produced before the kill: the
        # r03–r05 wedge was "timeout" with zero diagnosis, yet the dying
        # child had already printed the metadata-retry storm that named
        # the cause
        tail = ""
        if e.stderr:
            err_text = (e.stderr if isinstance(e.stderr, str)
                        else e.stderr.decode(errors="replace"))
            tail = " | ".join(err_text.strip().splitlines()[-4:])
        return None, ("timeout: " + tail if tail else "timeout")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-6:]
        kind = "backend" if BACKEND_ERR in (proc.stderr or "") + (proc.stdout or "") else "error"
        return None, f"{kind}: " + " | ".join(tail)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                break
    return None, "error: no JSON on stdout"


def run_suite(args):
    t_start = time.perf_counter()
    rows, events = {}, []
    wedged = False

    def elapsed():
        return time.perf_counter() - t_start

    def note(msg):
        events.append(f"[{elapsed():.0f}s] {msg}")
        print(f"bench: {msg}", file=sys.stderr, flush=True)

    # --- optional staged triage before any probe (bench --doctor) ---
    # each doctor stage runs in its own subprocess under its own hard
    # timeout, so even a wedged libtpu costs bounded suite time and the
    # artifact records WHICH bring-up stage wedged (cli/doctor.py)
    doctor_snap = None
    if getattr(args, "doctor", False):
        from mdi_llm_tpu.cli.doctor import collect_snapshot

        note("mdi-doctor --quick preflight")
        doctor_snap = collect_snapshot(quick=True)
        stage_line = " ".join(
            f"{r['name']}={r['status']}" for r in doctor_snap["stages"]
        )
        note(f"doctor: {'healthy' if doctor_snap['ok'] else 'UNHEALTHY'} "
             f"({stage_line})")

    # --- backend bring-up with retry-after-sleep in fresh interpreters ---
    # --probe-timeout is a HARD TOTAL cap, not a per-attempt window:
    # BENCH_r05 burned 900 s of a 1140 s suite because each attempt got the
    # full budget again (events showed attempts still starting at t=420 s
    # and t=900 s).  Every attempt now runs against the REMAINING budget,
    # retry sleeps draw from the same budget, and the CPU fallback starts
    # the moment the deadline passes — whatever --probe-retries says.
    tpu_ok = False
    probe_deadline = time.perf_counter() + args.probe_timeout
    attempts = max(1, args.probe_retries + 1)
    # per-attempt diagnostics banked into detail.probe: the r03–r05
    # TPU→CPU fallback wedge was undiagnosable from the artifact alone
    # (events only said "probe attempt N failed") — now every attempt
    # records its backend, error string and elapsed time
    probe_attempts = []
    # the r03–r05 wedge, diagnosed (r6): hosts with NO TPU mounted still
    # probed, and libtpu's bring-up burned the whole budget retrying GCE
    # metadata fetches 30x per tpu-env variable before admitting there
    # was nothing there.  Hardware evidence is a local filesystem/env
    # read — when no device node or TPU env var exists, skip probing
    # entirely and fall back in milliseconds (MDI_FORCE_TPU_PROBE=1
    # overrides, for exotic plugins that expose neither)
    hardware = _tpu_hardware_evidence()
    if not hardware["present"]:
        note("no TPU hardware evidence (no /dev/accel*, /dev/vfio, or TPU "
             "env); skipping probe, CPU fallback immediately")
        attempts = 0
    probe_env = None
    for attempt in range(attempts):
        remaining = probe_deadline - time.perf_counter()
        if remaining <= 0:
            note(f"probe budget ({args.probe_timeout:g}s total) exhausted; "
                 "falling back")
            break
        t_att = time.perf_counter()
        used_env = probe_env
        res, err = _child(["--probe"], timeout=remaining, env=used_env)
        det = (res or {}).get("detail", {})
        probe_attempts.append({
            "attempt": attempt + 1,
            "elapsed_s": round(time.perf_counter() - t_att, 2),
            "backend": det.get("backend"),
            "device": det.get("device"),
            "ok": res is not None,
            "error": err,
            "env": used_env,
        })
        if err and _MDS_WEDGE_SIGNATURE in err:
            # metadata retry storm: the next attempt skips the metadata
            # server (explicit env vars still win inside libtpu), turning
            # a budget-burning hang into a fast, diagnosable failure
            note("probe hit the GCE-metadata retry storm; retrying with "
                 "TPU_SKIP_MDS_QUERY=1")
            probe_env = {"TPU_SKIP_MDS_QUERY": "1"}
        # the tunnel plugin may report its platform as "tpu" or "axon"
        if res is not None and (
            det.get("backend") in ("tpu", "axon") or "TPU" in det.get("device", "")
        ):
            tpu_ok = True
            note(f"probe ok in {res['value']}s on {res['detail'].get('device')}")
            break
        note(f"probe attempt {attempt + 1} failed: {err or res}")
        # hung probes usually mean a wedged tunnel and further probes just
        # queue behind it — that risk is priced into the SMALL DEFAULT
        # budget; a raised --probe-retries is honored uniformly (timeouts
        # included) but can never stretch the TOTAL beyond --probe-timeout
        # or the suite-budget/3 ceiling below
        remaining = probe_deadline - time.perf_counter()
        if (remaining <= 0 or elapsed() > args.suite_budget / 3
                or attempt == attempts - 1):
            break  # no sleep after the final attempt: go straight to fallback
        time.sleep(min(60.0, remaining))

    selected = None if not args.rows else set(args.rows.split(","))

    if tpu_ok:
        for row in SUITE_ROWS:
            if selected and row["name"] not in selected:
                continue
            if wedged:
                rows[row["name"]] = {"error": "skipped: backend presumed wedged"}
                continue
            if elapsed() > args.suite_budget:
                rows[row["name"]] = {"error": "skipped: suite budget exhausted"}
                continue
            attempts = [[]] + row.get("ladder", [])
            result = None
            for extra in attempts:
                cfg_flags = row["flags"] + extra
                res, err = _child(cfg_flags, timeout=row["timeout"])
                if (res is None and err and err.startswith("backend")
                        and elapsed() <= args.suite_budget):
                    # backend dropped mid-suite: sleep and retry the SAME
                    # config in a fresh interpreter before degrading to the
                    # next ladder rung — a transient init failure must not
                    # cost the round its intended headline config
                    note(f"{row['name']} backend drop, retrying same config")
                    time.sleep(60)
                    res, err = _child(cfg_flags, timeout=row["timeout"])
                if res is not None:
                    result = res
                    note(f"{row['name']}{' ' + ' '.join(extra) if extra else ''}: "
                         f"{res['value']} {res['unit']}")
                    break
                note(f"{row['name']} ({' '.join(cfg_flags)}) failed: {err}")
                if err == "timeout":
                    # killing a child mid-compile is the known wedge trigger;
                    # assume the backend is now unusable and stop device work
                    wedged = True
                    result = {"error": "timeout (backend may be wedged)"}
                    break
                if elapsed() > args.suite_budget:
                    result = {"error": f"gave up (budget): {err}"}
                    break
            rows[row["name"]] = result if result is not None else {"error": err}
    else:
        note("TPU backend unavailable; running CPU fallback rows")
        # the flagship fallback gets its own degradation ladder: a 1-core
        # box cannot decode 1.1B at the r5 box's pace (r6: the B=4 rung
        # alone blew 900 s), and an un-losable suite still owes SOME
        # decode number — the last rung drops to pythia-14m, clearly
        # recorded in the row's own config detail
        res = err = None
        for flags, t in (
            (["--backend", "cpu", "--batch", "4", "--new-tokens", "48",
              "--chunk", "16", "--seq-len", "256"], 600),
            (["--backend", "cpu", "--batch", "2", "--new-tokens", "16",
              "--chunk", "8", "--seq-len", "128"], 420),
            (["--backend", "cpu", "--model", "pythia-14m", "--batch", "4",
              "--new-tokens", "64", "--chunk", "16", "--seq-len", "256"],
             420),
        ):
            res, err = _child(flags, timeout=t)
            if res is not None:
                note(f"cpu fallback decode ({' '.join(flags[1:])}): "
                     f"{res['value']} {res['unit']}")
                break
            note(f"cpu fallback decode ({' '.join(flags[1:])}) failed: {err}")
            if elapsed() > args.suite_budget:
                break
        rows["tinyllama-bf16-cpu-fallback"] = res if res is not None else {"error": err}
        # serving rows on the CPU backend too (r6): the serving-cb/open
        # ladders had NEVER banked an in-suite number because the
        # fallback only ran the flagship decode row — a pythia-14m
        # engine serves at tens of tok/s on CPU, so both serving shapes
        # fit in ~a minute and every suite run records the serving path
        # end-to-end whatever the backend (value comparability across
        # backends is what the clearly-marked row names are for)
        for name, flags, row_timeout in (
            ("serving-cb-cpu-fallback",
             ["--backend", "cpu", "--mode", "serve", "--model", "pythia-14m",
              "--batch", "4", "--seq-len", "256", "--new-tokens", "16",
              "--serve-requests", "8", "--serve-chunk", "4"], 600),
            ("serving-open-cpu-fallback",
             ["--backend", "cpu", "--mode", "serve-open", "--model",
              "pythia-14m", "--batch", "4", "--seq-len", "256",
              "--new-tokens", "16", "--serve-open-requests", "12",
              "--serve-chunk", "4"], 600),
        ):
            if elapsed() > args.suite_budget:
                rows[name] = {"error": "skipped: suite budget exhausted"}
                continue
            res, err = _child(flags, timeout=row_timeout)
            rows[name] = res if res is not None else {"error": err}
            if res is not None:
                note(f"{name}: {res['value']} {res['unit']}")

    # --- assemble the single output line ---
    def ok(name):
        r = rows.get(name)
        return r if r and "error" not in r else None

    headline = (ok("tinyllama-bf16") or ok("tinyllama-w8a8")
                or ok("ring-pipeline-m16") or ok("tinyllama-bf16-cpu-fallback")
                # a box too slow for any 1.1B decode fallback still has
                # serving numbers: better a marked serving headline than
                # "no measurement succeeded"
                or ok("serving-cb-cpu-fallback"))
    # either 8B row can carry the north star; report the better multiple
    north_rows = [r for r in (ok("llama3-8b-int8"), ok("llama3-8b-int4")) if r]
    north = max(north_rows, key=lambda r: r["vs_baseline"]) if north_rows else None
    if headline is None and north is not None:
        headline = north
    if headline is not None:
        out = {
            "metric": headline["metric"],
            "value": headline["value"],
            "unit": headline["unit"],
            "vs_baseline": headline["vs_baseline"],
        }
    else:
        out = {"metric": "decode tokens/sec/chip (no measurement succeeded)",
               "value": 0.0, "unit": "tokens/s/chip", "vs_baseline": 0.0}
    # provenance header: versions/host/env captured WITHOUT touching any
    # backend (importlib.metadata only) so even a suite that dies on a
    # dead backend records what environment produced it — trajectory
    # JSONs become comparable across machines/toolchains
    from mdi_llm_tpu.cli.doctor import provenance

    out["detail"] = {
        "rows": rows,
        "provenance": provenance(),
        "probe": {
            "attempts": probe_attempts,
            "budget_s": args.probe_timeout,
            "retries_allowed": args.probe_retries,
            "tpu_ok": tpu_ok,
            # host-local hardware evidence gating the probe (r6 wedge
            # diagnosis: probing a host with no TPU burns the budget in
            # libtpu's 30x-retry metadata fetches before failing)
            "hardware": hardware,
        },
        "north_star": {
            "target": f">= {NORTH_STAR_MULTIPLE}x Jetson-class 8B baseline "
                      f"({JETSON_8B_TOKENS_PER_S} tok/s, stated in bench.py)",
            "met": bool(north and north["vs_baseline"] >= NORTH_STAR_MULTIPLE),
            "value": north["value"] if north else None,
            "vs_jetson_8b": north["vs_baseline"] if north else None,
        },
        "suite_wall_s": round(elapsed(), 1),
        "events": events,
    }
    if doctor_snap is not None:
        out["detail"]["doctor"] = doctor_snap
    banked = collect_banked_artifacts()
    if banked:
        out["detail"]["banked_artifacts"] = banked
    return out


def collect_banked_artifacts():
    """Summarize suite JSONs committed under bench_results/ (measurements
    banked by earlier healthy-backend runs).  Attached to every suite
    output so a run that lands on a wedged backend — the way round 4 lost
    its TPU rows — still points at the hardware record."""
    bdir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_results")
    if not os.path.isdir(bdir):
        return None
    banked = {}
    for f in sorted(os.listdir(bdir)):
        if not f.endswith(".json"):
            continue
        try:
            with open(os.path.join(bdir, f)) as fh:
                data = json.load(fh)
            detail = data.get("detail") if isinstance(data, dict) else None
            rows_b = detail.get("rows") if isinstance(detail, dict) else None
            def _device_of(v):
                detail = v.get("detail")
                if not isinstance(detail, dict):
                    return None
                dev = detail.get("device")
                # serve rows carry a device BLOCK since PR 10; the banked
                # summary wants the one-line identity either way
                return dev.get("name") if isinstance(dev, dict) else dev

            keep = {
                k: {
                    "value": v.get("value"),
                    "unit": v.get("unit"),
                    "device": _device_of(v),
                }
                for k, v in (rows_b or {}).items()
                if isinstance(v, dict) and "value" in v
            }
        except Exception:
            # this helper runs at the very end of run_suite: a malformed
            # banked file must never cost the run its own measurements
            continue
        if keep:
            banked[f] = keep
    if not banked:
        return None
    return {
        "note": "earlier healthy-backend measurements committed in "
                "bench_results/ (see its README.md for provenance)",
        "runs": banked,
    }


def main():
    args = build_parser().parse_args()
    if args.direct:
        print(json.dumps(run_direct(args)), flush=True)
        return
    try:
        out = run_suite(args)
    except Exception as e:  # suite mode must never lose the round's artifact
        out = {"metric": "decode tokens/sec/chip (suite crashed)", "value": 0.0,
               "unit": "tokens/s/chip", "vs_baseline": 0.0,
               "detail": {"error": f"{type(e).__name__}: {e}"}}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
