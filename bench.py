"""Benchmark harness: decode throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Scenario (mirrors BASELINE.md's TinyLlama configuration): TinyLlama-1.1B
architecture, bf16, random weights (numerics identical to converted weights
for throughput purposes), batched recurrent decode of 8 samples — the
single-chip analog of the reference's "3-node recurrent pipeline,
n-samples≥3" runs.  `vs_baseline` compares against ~7 tokens/s aggregate,
the 3×Jetson-TX2 TinyLlama rate read off the reference's published
tokens-vs-time plot (assets/time_vs_tokens_TinyLlama.png; no numeric tables
exist — BASELINE.md).

Flags: --model/--batch/--prompt-len/--new-tokens/--pipeline N to bench the
pipeline engine instead of batched single-chip decode.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_TOKENS_PER_S = 7.0  # 3×Jetson TX2, TinyLlama, from the plot


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny-llama-1.1b")
    # B=16 measured 1388 tok/s/chip vs 880 at B=8 on v5e (r3); decode is
    # weight-bandwidth-bound so throughput grows with batch
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--pipeline", type=int, default=0, help="run N-stage pipeline engine")
    ap.add_argument(
        "--samples-per-slot", type=int, default=1,
        help="pipeline mode: samples batched per ring slot (M)",
    )
    ap.add_argument("--dtype", choices=("bfloat16", "float16", "float32"), default="bfloat16")
    ap.add_argument("--quantize", choices=("none", "int8", "w8a8"), default="none")
    ap.add_argument("--kv-dtype", choices=("auto", "bfloat16", "float16", "float32", "float8"), default="auto")
    ap.add_argument("--chunk", type=int, default=128, help="decode steps per jit call")
    ap.add_argument(
        "--mode", choices=("decode", "prefill"), default="decode",
        help="prefill: compare flash-attention prefill latency vs the XLA "
        "path at --prompt-len and verify greedy-token agreement",
    )
    args = ap.parse_args()

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.models import transformer

    dtype = {
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "float32": jnp.float32,
    }[args.dtype]
    from mdi_llm_tpu.cli._common import resolve_kv_dtype
    kv_dtype = resolve_kv_dtype(args.kv_dtype) or dtype
    cfg = Config.from_name(args.model)
    if args.quantize != "none":
        # build the int8 tree directly: an 8B-class model never exists in
        # f32/bf16, so Llama-3-8B fits one v5e chip for quantized benches
        from mdi_llm_tpu.ops.quant import init_quantized_params

        params = init_quantized_params(
            cfg, mode="w8" if args.quantize == "int8" else "w8a8", dtype=dtype
        )
        if not args.pipeline:
            # single-chip engine keeps the tree as-is: pin it on device once
            # (PipelineEngine re-splits host-side and places per stage)
            params = jax.device_put(params)
        quantize = "none"  # engines receive pre-quantized params
    else:
        params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        quantize = args.quantize
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, args.prompt_len).tolist()
        for _ in range(args.batch)
    ]

    if args.mode == "prefill":
        from mdi_llm_tpu.generation import Generator

        if args.pipeline:
            raise SystemExit("--mode prefill benches the single-chip engine; drop --pipeline")
        if args.prompt_len < 256:
            raise SystemExit(
                "--mode prefill needs --prompt-len >= 256 (the flash kernel "
                "only engages above the small-tile threshold)"
            )
        if jax.default_backend() != "tpu":
            print("warning: flash kernel needs TPU; both runs use the XLA path",
                  flush=True)

        def best_prefill(use_flash):
            use_flash = use_flash and jax.default_backend() == "tpu"
            eng = Generator(
                cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
                use_flash=use_flash, quantize=quantize,
            )
            outs, _ = eng.generate(prompts, 8, temperature=0.0)  # warmup+tokens
            best = float("inf")
            for _ in range(3):
                _, stats = eng.generate(prompts, 1, temperature=0.0)
                best = min(best, stats.prefill_s)
            return best, outs

        t_flash, toks_flash = best_prefill(True)
        t_xla, toks_xla = best_prefill(False)
        assert toks_flash == toks_xla, "flash prefill diverged from XLA tokens"
        print(
            json.dumps(
                {
                    "metric": f"prefill latency ({args.model}, B={args.batch}, T={args.prompt_len})",
                    "value": round(min(t_flash, t_xla) * 1000, 2),
                    "unit": "ms",
                    "vs_baseline": round(t_xla / t_flash, 2),
                    "detail": {
                        "flash_ms": round(t_flash * 1000, 2),
                        "xla_ms": round(t_xla * 1000, 2),
                        "flash_speedup": round(t_xla / t_flash, 2),
                        "tokens_agree": True,
                        "device": str(jax.devices()[0]),
                    },
                }
            )
        )
        return

    if args.pipeline:
        from mdi_llm_tpu.parallel.pipeline import PipelineEngine

        engine = PipelineEngine(
            cfg,
            params,
            n_stages=args.pipeline,
            max_seq_length=args.seq_len,
            cache_dtype=kv_dtype,
            quantize=quantize,
            samples_per_slot=args.samples_per_slot,
        )
        label = f"pipeline{args.pipeline}" + (
            f"xM{args.samples_per_slot}" if args.samples_per_slot > 1 else ""
        ) + (f"+{args.quantize}" if args.quantize != "none" else "")
    else:
        from mdi_llm_tpu.generation import Generator

        engine = Generator(
            cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
            quantize=quantize,
        )
        label = "batched-decode" + (
            f"+{args.quantize}" if args.quantize != "none" else ""
        )

    kwargs = {} if args.pipeline else {"chunk_size": args.chunk}
    # warmup with the run's own token budget: KV caches are sized to the run
    # (prompt+max_new bucket), so a shorter warmup would compile a different
    # cache shape and the timed run would recompile inside the measurement
    engine.generate(prompts, args.new_tokens, temperature=0.0, **kwargs)
    t0 = time.perf_counter()
    outs, stats = engine.generate(prompts, args.new_tokens, temperature=0.0, **kwargs)
    wall = time.perf_counter() - t0

    toks = sum(len(o) - args.prompt_len for o in outs)
    decode_tps = stats.tokens_generated / stats.decode_s if stats.decode_s else 0.0
    n_chips = max(1, args.pipeline)
    value = decode_tps / n_chips

    print(
        json.dumps(
            {
                "metric": f"decode tokens/sec/chip ({args.model}, B={args.batch}, {label})",
                "value": round(value, 2),
                "unit": "tokens/s/chip",
                "vs_baseline": round(value / REFERENCE_TOKENS_PER_S, 2),
                "detail": {
                    "total_tokens": toks,
                    "decode_tokens_per_s": round(decode_tps, 2),
                    "prefill_s": round(stats.prefill_s, 3),
                    "wall_s": round(wall, 2),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
