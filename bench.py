"""Benchmark harness: decode throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Scenario (mirrors BASELINE.md's TinyLlama configuration): TinyLlama-1.1B
architecture, bf16, random weights (numerics identical to converted weights
for throughput purposes), batched recurrent decode of 8 samples — the
single-chip analog of the reference's "3-node recurrent pipeline,
n-samples≥3" runs.  `vs_baseline` compares against ~7 tokens/s aggregate,
the 3×Jetson-TX2 TinyLlama rate read off the reference's published
tokens-vs-time plot (assets/time_vs_tokens_TinyLlama.png; no numeric tables
exist — BASELINE.md).

Flags: --model/--batch/--prompt-len/--new-tokens/--pipeline N to bench the
pipeline engine instead of batched single-chip decode.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_TOKENS_PER_S = 7.0  # 3×Jetson TX2, TinyLlama, from the plot


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="tiny-llama-1.1b")
    # decode is weight-bandwidth-bound so throughput grows with batch: v5e
    # r3 measured 880 (B=8) / 2283 (B=16) / 2727 (B=24) tok/s/chip.  B=32's
    # compile has wedged the remote-tunnel backend before — stay at 24.
    ap.add_argument("--batch", type=int, default=24)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--new-tokens", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--pipeline", type=int, default=0, help="run N-stage pipeline engine")
    ap.add_argument(
        "--samples-per-slot", type=int, default=1,
        help="pipeline mode: samples batched per ring slot (M)",
    )
    ap.add_argument("--dtype", choices=("bfloat16", "float16", "float32"), default="bfloat16")
    ap.add_argument("--quantize", choices=("none", "int8", "w8a8", "int4"), default="none")
    ap.add_argument("--kv-dtype", choices=("auto", "bfloat16", "float16", "float32", "float8"), default="auto")
    # decode default 256 measured 2283 tok/s/chip vs 2133 at 128 (v5e, r3):
    # longer scans amortize the host sync between dispatches.  Pipeline mode
    # defaults to 16: surplus ring rotations after a mid-chunk sample finish
    # are discarded, so long chunks deflate runs with early-stopping samples.
    ap.add_argument(
        "--chunk", type=int, default=None,
        help="decode steps per jit call (default 256; pipeline mode: "
        "steady-state ring rotations per jit call, default 16)",
    )
    ap.add_argument(
        "--mode", choices=("decode", "prefill"), default="decode",
        help="prefill: compare flash-attention prefill latency vs the XLA "
        "path at --prompt-len and verify greedy-token agreement",
    )
    args = ap.parse_args()
    if args.chunk is None:
        args.chunk = 16 if args.pipeline else 256

    from mdi_llm_tpu.config import Config
    from mdi_llm_tpu.models import transformer

    dtype = {
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "float32": jnp.float32,
    }[args.dtype]
    from mdi_llm_tpu.cli._common import resolve_kv_dtype
    kv_dtype = resolve_kv_dtype(args.kv_dtype) or dtype
    cfg = Config.from_name(args.model)
    if args.quantize != "none":
        # build the int8 tree directly: an 8B-class model never exists in
        # f32/bf16, so Llama-3-8B fits one v5e chip for quantized benches
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, init_quantized_params

        params = init_quantized_params(
            cfg, mode=FLAG_TO_MODE[args.quantize], dtype=dtype
        )
        if not args.pipeline:
            # single-chip engine keeps the tree as-is: pin it on device once
            # (PipelineEngine re-splits host-side and places per stage)
            params = jax.device_put(params)
        quantize = "none"  # engines receive pre-quantized params
    else:
        params = transformer.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
        quantize = args.quantize
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, args.prompt_len).tolist()
        for _ in range(args.batch)
    ]

    if args.mode == "prefill":
        from mdi_llm_tpu.generation import Generator

        if args.pipeline:
            raise SystemExit("--mode prefill benches the single-chip engine; drop --pipeline")
        if args.quantize != "none":
            raise SystemExit(
                "--mode prefill compares against an f32 reference forward, "
                "which does not exist for a quantized tree; drop --quantize"
            )
        if args.prompt_len < 256:
            raise SystemExit(
                "--mode prefill needs --prompt-len >= 256 (the flash kernel "
                "only engages above the small-tile threshold)"
            )
        limit = min(args.seq_len, cfg.block_size)
        if args.prompt_len >= limit:
            raise SystemExit(
                f"--prompt-len {args.prompt_len} must leave generation room "
                f"below min(--seq-len, context window) = {limit}; positions "
                "past the RoPE cache would be garbage"
            )
        if jax.default_backend() != "tpu":
            print("warning: flash kernel needs TPU; both runs use the XLA path",
                  flush=True)

        def best_prefill(use_flash):
            use_flash = use_flash and jax.default_backend() == "tpu"
            eng = Generator(
                cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
                use_flash=use_flash, quantize=quantize,
                # force the comparison at exactly --prompt-len (the engine's
                # auto threshold would silently fall back to XLA below 2k)
                flash_min_len=256,
            )
            eng.generate(prompts, 1, temperature=0.0)  # warmup
            best = float("inf")
            for _ in range(3):
                _, stats = eng.generate(prompts, 1, temperature=0.0)
                best = min(best, stats.prefill_s)
            return best

        # Numerics: the two attention implementations accumulate in different
        # orders, so bf16 token identity is not a meaningful invariant
        # (near-tie argmax flips are expected, especially on random weights
        # whose logits are near-uniform).  The meaningful check: flash must
        # be no less accurate than the XLA path against an f32 reference
        # forward (measured r3 on v5e: flash 0.0297 vs xla 0.0303 rel err —
        # statistically identical).
        batch_np = np.zeros((args.batch, args.prompt_len), np.int32)
        for i, p in enumerate(prompts):
            batch_np[i] = np.asarray(p, np.int32)

        # device-side reductions over the last <=512 prompt positions: full
        # (B, T, vocab) f32 logit tensors pulled to host would be multi-GB at
        # the shapes where flash matters
        n_check = min(args.prompt_len, 512)

        def prompt_logits(run_params, run_dtype, use_flash):
            kv0 = transformer.init_kv_cache(
                cfg, args.batch, args.prompt_len, dtype=run_dtype
            )

            def fwd(pr, t, kv):
                logits, _ = transformer.forward(
                    cfg, pr, t, jnp.zeros((args.batch,), jnp.int32), kv=kv,
                    fresh_prefill=True,
                    use_flash=use_flash and jax.default_backend() == "tpu",
                )
                # slice inside the jit so only the checked tail is ever
                # materialized (full (B,T,vocab) f32 is multi-GB at the
                # shapes where flash matters)
                return logits[:, -n_check:].astype(jnp.float32)

            return jax.jit(fwd)(run_params, jnp.asarray(batch_np), kv0)

        params_f32 = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32), params
        )
        lg_ref = prompt_logits(params_f32, jnp.float32, False)
        del params_f32
        scale_ = max(1e-6, float(jnp.max(jnp.abs(lg_ref))))

        def check(use_flash):
            lg = prompt_logits(params, kv_dtype, use_flash)
            err = float(jnp.max(jnp.abs(lg - lg_ref))) / scale_
            return err, jnp.argmax(lg, -1)

        err_f, am_f = check(True)
        err_x, am_x = check(False)
        del lg_ref
        agree = float(jnp.mean(am_f == am_x))
        assert err_f <= err_x * 1.5 + 1e-3, (
            f"flash prefill less accurate than XLA: {err_f} vs {err_x}"
        )

        t_flash = best_prefill(True)
        t_xla = best_prefill(False)
        print(
            json.dumps(
                {
                    "metric": f"prefill latency ({args.model}, B={args.batch}, T={args.prompt_len})",
                    "value": round(min(t_flash, t_xla) * 1000, 2),
                    "unit": "ms",
                    "vs_baseline": round(t_xla / t_flash, 2),
                    "detail": {
                        "flash_ms": round(t_flash * 1000, 2),
                        "xla_ms": round(t_xla * 1000, 2),
                        "flash_speedup": round(t_xla / t_flash, 2),
                        "flash_rel_err_vs_f32": round(err_f, 5),
                        "xla_rel_err_vs_f32": round(err_x, 5),
                        "argmax_agreement_bf16": round(agree, 5),
                        "device": str(jax.devices()[0]),
                    },
                }
            )
        )
        return

    if args.pipeline:
        from mdi_llm_tpu.parallel.pipeline import PipelineEngine

        engine = PipelineEngine(
            cfg,
            params,
            n_stages=args.pipeline,
            max_seq_length=args.seq_len,
            cache_dtype=kv_dtype,
            quantize=quantize,
            samples_per_slot=args.samples_per_slot,
            rotations_per_call=args.chunk,
        )
        label = f"pipeline{args.pipeline}" + (
            f"xM{args.samples_per_slot}" if args.samples_per_slot > 1 else ""
        ) + (f"+{args.quantize}" if args.quantize != "none" else "")
    else:
        from mdi_llm_tpu.generation import Generator

        engine = Generator(
            cfg, params, max_seq_length=args.seq_len, cache_dtype=kv_dtype,
            quantize=quantize,
        )
        label = "batched-decode" + (
            f"+{args.quantize}" if args.quantize != "none" else ""
        )

    kwargs = {} if args.pipeline else {"chunk_size": args.chunk}
    # warmup with the run's own token budget: KV caches are sized to the run
    # (prompt+max_new bucket), so a shorter warmup would compile a different
    # cache shape and the timed run would recompile inside the measurement
    engine.generate(prompts, args.new_tokens, temperature=0.0, **kwargs)
    t0 = time.perf_counter()
    outs, stats = engine.generate(prompts, args.new_tokens, temperature=0.0, **kwargs)
    wall = time.perf_counter() - t0

    toks = sum(len(o) - args.prompt_len for o in outs)
    decode_tps = stats.tokens_generated / stats.decode_s if stats.decode_s else 0.0
    n_chips = max(1, args.pipeline)
    value = decode_tps / n_chips

    print(
        json.dumps(
            {
                "metric": f"decode tokens/sec/chip ({args.model}, B={args.batch}, {label})",
                "value": round(value, 2),
                "unit": "tokens/s/chip",
                "vs_baseline": round(value / REFERENCE_TOKENS_PER_S, 2),
                "detail": {
                    "total_tokens": toks,
                    "decode_tokens_per_s": round(decode_tps, 2),
                    "prefill_s": round(stats.prefill_s, 3),
                    "wall_s": round(wall, 2),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
