#!/usr/bin/env bash
# Example multi-chip training launch (≡ reference src/distr_train.sh, which
# wraps torchrun for DDP NanoLlama training).  On TPU the mesh replaces
# torchrun: one process per host, XLA inserts the gradient collectives.
set -euo pipefail

CKPT=${1:-checkpoints/custom/NanoLlama}
DATA=${2:-data/shakespeare}

python -m mdi_llm_tpu.cli.train \
    --ckpt "$CKPT" \
    --dataset "$DATA" \
    --mesh dp=-1 \
    --batch-size 8 --grad-acc-steps 4 \
    --max-iters 2000 --ckpt-interval 200
