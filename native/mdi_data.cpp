// Native data-loading runtime for mdi_llm_tpu.
//
// TPU-native counterpart of the reference's Python data path
// (/root/reference/src/sub/utils/data_loader.py:70-126: np.memmap +
// per-batch Python loop).  This library mmaps the tokenized .bin corpus and
// gathers random (x, y) next-token training windows directly into
// caller-provided buffers — no Python-loop per sample, no intermediate
// copies, deterministic given a seed (splitmix64 → xorshift), so training
// batches are reproducible across the ctypes and pure-NumPy loaders.
//
// Build: make -C native    (produces libmdi_data.so)
// ABI: plain C, used from Python via ctypes (mdi_llm_tpu/utils/native_loader.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct BinFile {
  void* base = nullptr;
  size_t bytes = 0;
  int fd = -1;
  int dtype_size = 2;  // uint16 tokens by default (vocab < 65536)
};

inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

inline uint32_t token_at(const BinFile* f, size_t idx) {
  if (f->dtype_size == 2)
    return reinterpret_cast<const uint16_t*>(f->base)[idx];
  return reinterpret_cast<const uint32_t*>(f->base)[idx];
}

}  // namespace

extern "C" {

// Open a token bin file; dtype_size is 2 (uint16) or 4 (uint32).
// Returns an opaque handle (heap pointer) or null on failure.
void* mdi_open_bin(const char* path, int dtype_size) {
  if (dtype_size != 2 && dtype_size != 4) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(base, st.st_size, MADV_RANDOM);
  BinFile* f = new BinFile();
  f->base = base;
  f->bytes = static_cast<size_t>(st.st_size);
  f->fd = fd;
  f->dtype_size = dtype_size;
  return f;
}

// Number of tokens in the file.
int64_t mdi_num_tokens(void* handle) {
  auto* f = static_cast<BinFile*>(handle);
  return f ? static_cast<int64_t>(f->bytes / f->dtype_size) : -1;
}

// Gather `batch` random windows of `block` tokens: x[i] = data[o..o+block),
// y[i] = data[o+1..o+block+1).  Outputs are int32 row-major
// (batch, block).  Deterministic in `seed`.  Returns 0 on success.
int mdi_sample_batch(void* handle, int64_t batch, int64_t block, uint64_t seed,
                     int32_t* out_x, int32_t* out_y) {
  auto* f = static_cast<BinFile*>(handle);
  if (!f || batch <= 0 || block <= 0) return 1;
  const int64_t n = mdi_num_tokens(handle);
  if (n <= block + 1) return 2;
  uint64_t state = seed ? seed : 0x853c49e6748fea9bULL;
  const uint64_t span = static_cast<uint64_t>(n - block - 1);
  for (int64_t b = 0; b < batch; ++b) {
    const uint64_t off = splitmix64(state) % span;
    int32_t* xr = out_x + b * block;
    int32_t* yr = out_y + b * block;
    if (f->dtype_size == 2) {
      const uint16_t* src = reinterpret_cast<const uint16_t*>(f->base) + off;
      for (int64_t t = 0; t < block; ++t) {
        xr[t] = src[t];
        yr[t] = src[t + 1];
      }
    } else {
      const uint32_t* src = reinterpret_cast<const uint32_t*>(f->base) + off;
      for (int64_t t = 0; t < block; ++t) {
        xr[t] = static_cast<int32_t>(src[t]);
        yr[t] = static_cast<int32_t>(src[t + 1]);
      }
    }
  }
  return 0;
}

// Sequential read of `count` tokens starting at `start` (validation sweeps).
int mdi_read_tokens(void* handle, int64_t start, int64_t count, int32_t* out) {
  auto* f = static_cast<BinFile*>(handle);
  if (!f || start < 0 || count < 0) return 1;
  const int64_t n = mdi_num_tokens(handle);
  if (start + count > n) return 2;
  for (int64_t i = 0; i < count; ++i) out[i] = token_at(f, start + i);
  return 0;
}

void mdi_close_bin(void* handle) {
  auto* f = static_cast<BinFile*>(handle);
  if (!f) return;
  munmap(f->base, f->bytes);
  ::close(f->fd);
  delete f;
}

}  // extern "C"
